"""Framework benchmark. Prints ONE JSON line.

Two halves:

1. TPU compute (runs when a TPU is attached — the driver's bench host):
   - pallas flash-attention kernel vs the XLA reference attention
     (ops/attention.py mha_reference) at 2k/4k bf16: wall time, achieved
     TFLOP/s, MFU, speedup (reported as `flash_vs_xla_attention_4k` — it is
     a KERNEL-vs-XLA-attention number, not a framework-vs-framework one;
     plus the VERDICT-r1 `kernel_mfu` acceptance number),
   - long-context: flash at 8k seq, where the score-materializing path
     cannot run at all on one chip,
   - flagship train step (models/transformer.py + make_train_step):
     tokens/s and estimated model FLOPs utilization.
   Timing methodology: this host reaches the chip through a dispatch tunnel
   whose per-call round-trip is LARGE and VARIABLE (~90-120 ms observed), so
   naive per-call timing measures the tunnel and even single-loop timing
   carries the round-trip as an additive error. Every measurement therefore
   times TWO jitted lax.fori_loop lengths (n1, n2 iterations chained on
   device) and reports the slope (t2 - t1)/(n2 - n1): the constant tunnel
   cost cancels exactly. Endpoints are min-of-reps (robust to tunnel jitter
   and shared-chip contention). Round 2 under-reported every kernel number
   2-5x for exactly this reason (31.5 "TF/s" at 8k that remeasures at ~112).

2. Control plane (always runs): Notebook CR -> slice mesh-ready p50 against
   the in-process SimCluster — the full operator path (admission webhook ->
   reconcilers -> gang scheduler -> kubelet -> probe agents over real
   sockets -> device-visibility readiness gate). Reported on its own terms:
   an in-process sim latency, NOT comparable to a live-cluster number (the
   reference publishes no benchmarks at all, SURVEY §6).

vs_baseline for the headline metric is 1.0 by construction: the reference
framework publishes no comparable training-throughput number, so there is no
framework-vs-framework speedup to report. The measured kernel speedup over
the XLA reference implementation of the same op (the baseline a JAX user
gets without the pallas kernel) is reported separately and explicitly as
`flash_vs_xla_attention_4k` — earlier artifacts surfaced it as a top-level
`speedup_vs_reference`, which read as a framework comparison it never was.
"""
from __future__ import annotations

import json
import os as _os
import statistics
import time

# pkgutil-style package root: the driver runs this file as a SCRIPT
# (`python bench.py`), so `bench/` can't be a regular package without
# shadowing it — setting __path__ makes `import bench.ledger` resolve
# bench/ledger.py as a submodule of this module (ISSUE 15 ledger)
__path__ = [_os.path.join(_os.path.dirname(_os.path.abspath(__file__)), "bench")]

V5E_PEAK_FLOPS = 197e12  # bf16 peak, TPU v5e chip
V5E_HBM_GBPS = 819  # HBM bandwidth, TPU v5e chip (GB/s)

SINGLE_HOST_NOTEBOOKS = 16  # v5e-4 each
MULTI_HOST_NOTEBOOKS = 4  # v5p-32 each (4 hosts x 4 chips)


# ---------------------------------------------------------------------------
# TPU compute half
# ---------------------------------------------------------------------------


def _bench_slope(f, args, fetch, n1=10, n2=110, reps=4):
    """Per-iteration device time via the two-length slope (see module
    docstring): time a jitted fori_loop at n1 and n2 chained iterations,
    min-of-reps each endpoint, return (t2 - t1)/(n2 - n1)."""
    import jax

    from jax import lax

    def make(iters):
        loop = jax.jit(
            lambda *a: lax.fori_loop(0, iters, lambda i, x: f(x, *a[1:]), a[0])
        )
        fetch(loop(*args))  # compile + warm

        def run():
            t0 = time.perf_counter()
            fetch(loop(*args))
            return time.perf_counter() - t0

        return run

    r1, r2 = make(n1), make(n2)
    t1 = min(r1() for _ in range(reps))
    t2 = min(r2() for _ in range(reps))
    return (t2 - t1) / (n2 - n1)


def bench_kernels():
    import functools

    import jax
    import jax.numpy as jnp

    from odh_kubeflow_tpu.ops.attention import flash_attention, mha_reference

    def fetch(x):
        float(jnp.sum(x.astype(jnp.float32)))  # host fetch = true completion

    key = jax.random.PRNGKey(0)
    out = {}

    def qkv(b, s, h, hk, d=128):
        q = jax.random.normal(key, (b, s, h, d), jnp.bfloat16)
        k = jax.random.normal(key, (b, s, hk, d), jnp.bfloat16)
        v = jax.random.normal(key, (b, s, hk, d), jnp.bfloat16)
        return q, k, v

    def time_flash(args, b, s, h, n2):
        flops = 2 * b * h * s * s * 128  # causal
        t = _bench_slope(
            functools.partial(flash_attention, causal=True), args, fetch, n2=n2
        )
        return t, flops

    # vs the XLA reference attention at sizes where it still compiles
    for tag, (b, s, h), n2 in (("2k", (4, 2048, 8), 400), ("4k", (4, 4096, 8), 150)):
        q, k, v = qkv(b, s, h, h)
        t_flash, flops = time_flash((q, k, v), b, s, h, n2)
        t_ref = _bench_slope(
            functools.partial(mha_reference, causal=True), (q, k, v), fetch,
            n2=max(40, n2 // 4),
        )
        out[tag] = {
            "flash_ms": round(t_flash * 1e3, 3),
            "xla_reference_ms": round(t_ref * 1e3, 3),
            "flash_tflops": round(flops / t_flash / 1e12, 1),
            "mfu": round(flops / t_flash / V5E_PEAK_FLOPS, 3),
            "speedup": round(t_ref / t_flash, 2),
        }

    # compute-bound points: 8k (the materializing path cannot run at all on
    # one chip), 8k grouped-query (K/V streamed at kv_heads width — the
    # training-path GQA HBM win), and 16k long-context
    for tag, (b, s, h, hk), n2 in (
        ("8k", (4, 8192, 8, 8), 110),
        ("8k_gqa", (4, 8192, 16, 4), 60),
        ("16k", (2, 16384, 8, 8), 40),
    ):
        t, flops = time_flash(qkv(b, s, h, hk), b, s, h, n2)
        out[tag] = {
            "flash_ms": round(t * 1e3, 3),
            "flash_tflops": round(flops / t / 1e12, 1),
            "mfu": round(flops / t / V5E_PEAK_FLOPS, 3),
        }
        if tag == "8k":
            out[tag]["xla_reference"] = "fails to compile (8k scores > HBM)"

    # fwd+bwd at 8k: training spends most of its attention time in the two
    # backward kernels (ops/attention.py dq/dkv) — a forward-only point says
    # nothing about them (VERDICT r3 missing #2). The carry threads q/k/v
    # through their own grads so no pallas call is loop-invariant (hoisting)
    # or dead (DCE) — see the slope-method traps in the module docstring.
    b, s, h = 4, 8192, 8
    q, k, v = qkv(b, s, h, h)

    def attn_loss(q_, k_, v_):
        return jnp.sum(flash_attention(q_, k_, v_, causal=True).astype(jnp.float32))

    grad_qkv = jax.grad(attn_loss, argnums=(0, 1, 2))

    def fwd_bwd(carry):
        q_, k_, v_ = carry
        dq, dk, dv = grad_qkv(q_, k_, v_)
        # 1e-30 scales underflow in bf16 — the ADD is structural (defeats
        # hoisting/DCE), not numeric
        return (q_ + dq * 1e-30, k_ + dk * 1e-30, v_ + dv * 1e-30)

    def fetch_tree(t):
        for leaf in jax.tree_util.tree_leaves(t):
            float(jnp.sum(leaf.astype(jnp.float32)))

    t_fb = _bench_slope(lambda c: fwd_bwd(c), ((q, k, v),), fetch_tree, n2=40)
    # 7 block-matmul units (2 fwd: qk, pv; 5 bwd: recompute-qk, dp, dq, dk,
    # dv), each b*h*s*s*d causal-half FLOPs
    fb_flops = 7 * b * h * s * s * 128
    out["8k_fwd_bwd"] = {
        "fwd_bwd_ms": round(t_fb * 1e3, 3),
        "tflops": round(fb_flops / t_fb / 1e12, 1),
        "mfu": round(fb_flops / t_fb / V5E_PEAK_FLOPS, 3),
    }

    # calibration: an 8192^3 matmul is this stack's practical ceiling at the
    # compute-bound grain; flash-vs-this ratio is the honest efficiency read
    # (the diagonal blocks of a blocked causal kernel are half-wasted by
    # construction, so ~0.9x the non-causal kernel ceiling is the scheme max)
    m = 8192
    a = jax.random.normal(key, (m, m), jnp.bfloat16)
    t_mm = _bench_slope(
        lambda x, w: (x @ w).astype(jnp.bfloat16), (a, a), fetch, n2=110
    )
    mm_tflops = 2 * m**3 / t_mm / 1e12
    # explicit name: this is the flash KERNEL vs XLA's attention at the
    # largest size both compile (4k), not a framework-vs-framework speedup —
    # and it is the 4k POINT, not the best across sizes
    out["flash_vs_xla_attention_4k"] = out["4k"]["speedup"]
    # headline MFU from the compute-bound 8k point, NOT the dispatch-floored
    # small sizes
    out["kernel_mfu"] = out["8k"]["mfu"]
    out["calibration"] = {
        "matmul_ceiling_tflops": round(mm_tflops, 1),
        "matmul_ceiling_mfu": round(mm_tflops * 1e12 / V5E_PEAK_FLOPS, 3),
        "flash_8k_vs_matmul_ceiling": round(
            out["8k"]["flash_tflops"] / mm_tflops, 2
        ),
        "flash_8k_fwd_bwd_vs_matmul_ceiling": round(
            out["8k_fwd_bwd"]["tflops"] / mm_tflops, 2
        ),
        "flash_16k_vs_matmul_ceiling": round(
            out["16k"]["flash_tflops"] / mm_tflops, 2
        ),
    }
    return out


def bench_train_step():
    import jax
    import jax.numpy as jnp

    from odh_kubeflow_tpu.models import (
        TransformerConfig,
        init_params,
        make_train_step,
    )

    import os

    # A/B knob for the remat policy without code edits (VERDICT r4 #9):
    # "" = save nothing, "dots" = matmul outputs (measured no-op: every dot
    # here carries a batch dim), "flash" = the flash kernel's (out, lse)
    # residuals so the backward skips the forward-kernel recompute, "attn" =
    # flash + post-projection output. Default is the measured winner on
    # v5e-1 (r5 A/B: "" 193.5 ms, dots 197.2, attn-old 197.2, flash ~179).
    remat_policy = os.environ.get("BENCH_REMAT_POLICY", "flash")
    cfg = TransformerConfig(
        vocab=32768,
        d_model=1024,
        n_layers=8,
        n_heads=8,
        d_ff=4096,
        max_seq=2048,
        dtype=jnp.bfloat16,
        use_flash=True,
        remat=True,
        remat_policy=remat_policy,
    )
    batch, seq = 8, 2048
    params = init_params(jax.random.PRNGKey(0), cfg)
    step, opt = make_train_step(cfg)
    opt_state = opt.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0, cfg.vocab)
    batch_d = {"tokens": tokens}
    from odh_kubeflow_tpu.analysis import hotregions
    from odh_kubeflow_tpu.utils import jaxguard

    # donate params + opt_state: the step overwrites both wholesale, so
    # donation lets XLA alias the update in place instead of holding two
    # copies of every weight/optimizer buffer (the classic missed-donation
    # bug jaxlint's donation-discipline checker exists for); the guard's
    # compile counter doubles as the retrace regression gate below
    compile_base = jaxguard.compile_count("bench.train_step")
    step = jaxguard.jit(step, region="bench.train_step", donate_argnums=(0, 1))

    from odh_kubeflow_tpu.utils import profiler

    # PROFILE=1 (ISSUE 15): the whole measurement is one bench.train_step
    # region decomposed into warm_compile -> slope_short -> slope_long
    # phases — the report's where_time_went shows whether a slow bench run
    # spent its time compiling or stepping
    with profiler.region("bench.train_step", consumer="bench"):
        with profiler.phase("warm_compile"):
            params, opt_state, loss = step(params, opt_state, batch_d)
            float(loss)

        # two-length slope (see module docstring): steps chain through
        # params/opt_state on device; the tunnel round-trip cancels
        def run_n(n):
            nonlocal params, opt_state, loss
            t0 = time.perf_counter()
            for _ in range(n):
                params, opt_state, loss = step(params, opt_state, batch_d)
            float(loss)  # host fetch = true completion
            return time.perf_counter() - t0

        with profiler.phase("warm_steady"):
            run_n(1)
        with profiler.phase("slope_short"):
            t_short = min(run_n(2) for _ in range(2))
        with profiler.phase("slope_long"):
            t_long = min(run_n(14) for _ in range(2))
    step_s = (t_long - t_short) / 12

    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    tokens_per_s = batch * seq / step_s
    # 6*P per token (fwd+bwd) + attention term 12*L*d*s
    flops_per_token = 6 * n_params + 12 * cfg.n_layers * cfg.d_model * seq
    mfu = flops_per_token * tokens_per_s / V5E_PEAK_FLOPS
    # publish to the shared telemetry registry (step-time histogram +
    # tokens/s + MFU gauges land on any /metrics scrape of this process)
    from odh_kubeflow_tpu.tpu import telemetry

    telemetry.observe_train_step(step_s, tokens=batch * seq, mfu_est=mfu)
    # the declared compile budget (analysis/hotregions.py): the step traces
    # exactly once; a retrace would poison the two-length slope AND means
    # something shape-varying leaked into the step — fail the bench, not
    # the vibe
    budget = hotregions.get("bench.train_step").compile_budget
    recompiles = jaxguard.compile_count("bench.train_step") - compile_base
    assert recompiles <= budget, (
        f"train step traced {recompiles}x, compile budget {budget} "
        "(analysis/hotregions.py) — a retrace hazard landed in the step"
    )
    return {
        "tokens_per_s": round(tokens_per_s),
        "step_ms": round(step_s * 1e3, 1),
        "params_m": round(n_params / 1e6, 1),
        "batch": batch,
        "seq": seq,
        "mfu_est": round(mfu, 3),
        "remat_policy": remat_policy or "none-saved",
        "final_loss": round(float(loss), 3),
        "train_step_recompiles": recompiles,
        "train_step_compile_budget": budget,
        "donated": "params+opt_state (aliased in place; JAXGUARD audits)",
    }


def bench_attention_memory():
    """Compiled-memory evidence that the flash path keeps per-device
    attention memory LINEAR in sequence length (VERDICT r3 next #3's bench
    point; the kernel ring composes these same blocks per visit): XLA's
    memory analysis for value_and_grad of the attention op at 4k/8k/16k.
    A score-materializing path grows temp ~4x per seq doubling; flash grows
    ~2x (inputs/outputs/lse only)."""
    import jax
    import jax.numpy as jnp

    from odh_kubeflow_tpu.ops.attention import flash_attention

    out = {}
    prev = None
    for s in (4096, 8192, 16384):
        shp = jax.ShapeDtypeStruct((2, s, 8, 128), jnp.bfloat16)

        def loss(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, causal=True).astype(jnp.float32)
            )

        compiled = jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(
            shp, shp, shp
        ).compile()
        try:
            temp = compiled.memory_analysis().temp_size_in_bytes
        except Exception:
            temp = None
        key = f"seq{s//1024}k"
        out[key] = {"temp_mb": round(temp / 2**20, 1) if temp else None}
        if temp and prev:
            out[key]["growth_vs_half_seq"] = round(temp / prev, 2)
        prev = temp
    out["note"] = (
        "fwd+bwd temp allocation per XLA memory analysis; ~2x per seq "
        "doubling = linear attention memory (a materialized score matrix "
        "would grow ~4x)"
    )
    return out


def bench_moe_train_step():
    """Mixture-of-Experts train step on the chip (VERDICT r3 missing #2 /
    next #4): 201M-active-class config, E=8 top-2 experts. Reports tokens/s,
    MFU over ACTIVE FLOPs, the dispatch share (routing + scatter/gather
    timed alone at the same token count), and the capacity-drop rate at the
    first layer's true inputs."""
    import jax
    import jax.numpy as jnp

    from odh_kubeflow_tpu.models import (
        MoEConfig,
        TransformerConfig,
        init_params,
        make_train_step,
    )

    cfg = TransformerConfig(
        vocab=32768,
        d_model=1024,
        n_layers=8,
        n_heads=8,
        d_ff=2048,  # per-expert hidden; top-2 of E=8 => dense-4096-class active
        max_seq=2048,
        dtype=jnp.bfloat16,
        use_flash=True,
        remat=True,
        moe=MoEConfig(n_experts=8, experts_per_token=2, capacity_factor=1.25),
    )
    batch, seq = 8, 2048
    params = init_params(jax.random.PRNGKey(0), cfg)
    step, opt = make_train_step(cfg)
    opt_state = opt.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0, cfg.vocab)
    batch_d = {"tokens": tokens}
    step = jax.jit(step)

    params, opt_state, loss = step(params, opt_state, batch_d)
    float(loss)

    def run_n(n):
        nonlocal params, opt_state, loss
        t0 = time.perf_counter()
        for _ in range(n):
            params, opt_state, loss = step(params, opt_state, batch_d)
        float(loss)
        return time.perf_counter() - t0

    run_n(1)
    t_short = min(run_n(2) for _ in range(2))
    t_long = min(run_n(10) for _ in range(2))
    step_s = (t_long - t_short) / 8

    # active params: everything except experts, plus top-k of the E experts
    n_total = sum(x.size for x in jax.tree_util.tree_leaves(params))
    expert_sz = sum(
        params["layers"][k].size for k in ("we_gate", "we_up", "we_out")
    )
    n_active = n_total - expert_sz + expert_sz * cfg.moe.experts_per_token // (
        cfg.moe.n_experts
    )
    tokens_per_s = batch * seq / step_s
    flops_per_token = 6 * n_active + 12 * cfg.n_layers * cfg.d_model * seq
    mfu = flops_per_token * tokens_per_s / V5E_PEAK_FLOPS

    # dispatch share: routing + dispatch/combine (no expert matmuls) timed
    # alone at the same per-layer token count
    from odh_kubeflow_tpu.models.moe import dispatch_only, routing_stats

    moe_params = jax.tree_util.tree_map(
        lambda p: p[0], {k: params["layers"][k] for k in
                         ("router", "we_gate", "we_up", "we_out")}
    )
    x_tokens = params["embed"].astype(cfg.dtype)[tokens]  # (b, s, d) stand-in

    def fetch(x):
        float(jnp.sum(x.astype(jnp.float32)))

    t_disp = _bench_slope(
        lambda x: dispatch_only(x, moe_params, cfg.moe_resolved),
        (x_tokens,), fetch, n2=40,
    )
    # per-step dispatch time: L layers, fwd + ~2x bwd
    dispatch_share = 3 * cfg.n_layers * t_disp / step_s

    # dense-vs-indexed dispatch A/B at the bench token count (VERDICT r4
    # #7): the dense one-hot einsums are what cfg.dispatch="dense" would run
    # on a live ep axis; the indexed path is the shipped default
    # (_moe_ffn_ep_indexed). Timed here single-chip at b*s = 16k tokens.
    t_disp_dense = _bench_slope(
        lambda x: dispatch_only(x, moe_params, cfg.moe_resolved, dense=True),
        (x_tokens,), fetch, n2=20,
    )

    stats = routing_stats(x_tokens, moe_params, cfg.moe_resolved)
    return {
        "tokens_per_s": round(tokens_per_s),
        "step_ms": round(step_s * 1e3, 1),
        "params_total_m": round(n_total / 1e6, 1),
        "params_active_m": round(n_active / 1e6, 1),
        "mfu_est_active": round(mfu, 3),
        "dispatch_share_est": round(dispatch_share, 3),
        "dispatch_paths_16k_tokens": {
            "indexed_ms": round(t_disp * 1e3, 3),
            "dense_ms": round(t_disp_dense * 1e3, 3),
            "dense_over_indexed": round(t_disp_dense / max(t_disp, 1e-9), 2),
            "note": "indexed is the live-ep GSPMD path "
                    "(models/moe._moe_ffn_ep_indexed); dense kept as "
                    "cfg.dispatch='dense' for A/B",
        },
        "capacity_drop_rate": round(float(stats["drop_rate"]), 4),
        "final_loss": round(float(loss), 3),
        "n_experts": cfg.moe.n_experts,
        "experts_per_token": cfg.moe.experts_per_token,
    }


def bench_decode():
    """KV-cache autoregressive decoding: tokens/s for a whole generate call
    (prefill + scanned decode loop, ONE compiled program).

    Completion is a host scalar fetch, NOT block_until_ready — through the
    per-dispatch tunnel block_until_ready can return before the program
    finishes (observed: absurd token rates). Per-token time comes from the
    two-length slope (max_new 32 vs 128, min-of-reps): prefill time AND the
    large, variable tunnel round-trip cancel exactly — the old full-minus-
    prefill subtraction carried the round-trip's ±100 ms jitter, i.e.
    ±0.8 ms/token of pure noise."""
    import jax
    import jax.numpy as jnp

    from odh_kubeflow_tpu.models import TransformerConfig, generate, init_params

    cfg = TransformerConfig(
        vocab=32768,
        d_model=1024,
        n_layers=8,
        n_heads=8,
        d_ff=4096,
        max_seq=2048,
        dtype=jnp.bfloat16,
        use_flash=True,
        remat=False,
    )
    return _decode_point(cfg, batch=8, prompt_len=128, max_new=128, short_new=32,
                         max_seq=256)


def bench_decode_long_cache():
    """Long-cache decode (VERDICT r3 next #6): a 4k-slot cache where cache
    reads, not weights, dominate the per-token HBM traffic — exactly where
    the flat (batch*kv_heads, max_seq, head_dim) layout claims its win
    (models/decode.py)."""
    import jax.numpy as jnp

    from odh_kubeflow_tpu.models import TransformerConfig

    cfg = TransformerConfig(
        vocab=32768,
        d_model=1024,
        n_layers=8,
        n_heads=8,
        d_ff=4096,
        max_seq=4096,
        dtype=jnp.bfloat16,
        use_flash=True,
        remat=False,
    )
    return _decode_point(cfg, batch=8, prompt_len=2048, max_new=128,
                         short_new=32, max_seq=4096)


def _decode_point(cfg, batch, prompt_len, max_new, short_new, max_seq):
    import jax
    import jax.numpy as jnp

    from odh_kubeflow_tpu.models import generate, init_params

    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (batch, prompt_len), 0, cfg.vocab)

    def fetch(x):
        int(jnp.sum(x))  # host fetch = true completion

    def timed(n_new):
        # fixed max_seq so both lengths share cache shapes
        def run():
            t0 = time.perf_counter()
            fetch(generate(params, prompt, cfg, max_new=n_new, max_seq=max_seq))
            return time.perf_counter() - t0

        run()  # compile + warm
        return min(run() for _ in range(4))

    t_long = timed(max_new)
    t_short = timed(short_new)
    decode_s = max(t_long - t_short, 1e-9) * (max_new - 1) / (max_new - short_new)
    elapsed = t_long  # wall for the full generate (incl. one tunnel trip)
    prefill_s = max(t_long - decode_s, 0.0)
    # per-step HBM floor: every decode token re-reads all params + the cache
    # (the FULL static max_seq extent — masked positions still stream).
    # The embed table doesn't stream — decode gathers `batch` rows — so it's
    # excluded (unembed DOES stream through the logits matmul).
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    n_streamed = n_params - params["embed"].size
    bytes_per_step = 2 * n_streamed + 2 * 2 * cfg.n_layers * batch * (
        max_seq
    ) * cfg.kv_heads * cfg.head_dim
    hbm_util = bytes_per_step / (decode_s / (max_new - 1)) / V5E_HBM_GBPS / 1e9
    from odh_kubeflow_tpu.tpu import telemetry

    telemetry.observe_decode_step(decode_s / (max_new - 1), tokens=batch)
    return {
        "generate_tokens_per_s": round(batch * max_new / elapsed),
        "decode_only_tokens_per_s": round(batch * (max_new - 1) / decode_s),
        "decode_per_token_ms": round(decode_s / (max_new - 1) * 1e3, 2),
        "hbm_util_est": round(hbm_util, 3),
        "cache_bytes_mb": round(
            2 * 2 * cfg.n_layers * batch * max_seq * cfg.kv_heads * cfg.head_dim
            / 1e6
        ),
        # derived as t_long - decode_s: carries ONE tunnel round-trip
        # (~90-120 ms) on top of the actual prompt forward
        "prefill_ms_incl_tunnel_rtt": round(prefill_s * 1e3, 1),
        "batch": batch,
        "prompt_len": prompt_len,
        "max_new": max_new,
        "max_seq": max_seq,
    }


def _bench_fleet_episode():
    """ISSUE 16 serving-fleet numbers, scripted and deterministic.

    Two headlines ride this episode (bench/ledger.py):

    - **router_added_latency_p50_ms** — the per-request tax of the
      health-aware token router over the bare engine at the same request
      shape: p50(router path) - p50(direct submit/wait). Signal scoring,
      breaker bookkeeping, and the result wait loop are all it can spend;
      the tolerance is wide because sub-millisecond host scheduling noise
      dominates an in-process measurement.
    - **scale_up_reaction_s** — hot autoscaler tick to new replica
      Serving: the annotation write, the endpoint controller's warm bind
      from the slice pool, and gang readiness, end to end.
    """
    import jax
    import jax.numpy as jnp

    from odh_kubeflow_tpu.models import TransformerConfig, init_params
    from odh_kubeflow_tpu.serving.engine import ServingEngine
    from odh_kubeflow_tpu.serving.router import TokenRouter

    tiny = TransformerConfig(
        vocab=256, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=64, max_seq=128, dtype=jnp.float32, use_flash=False,
        remat=False,
    )
    params = init_params(jax.random.PRNGKey(0), tiny)
    prompt = [1, 2, 3, 4, 5, 6, 7, 8]
    n_req, max_new = 40, 8

    def pct50(xs):
        xs = sorted(xs)
        return xs[len(xs) // 2]

    engines = [
        ServingEngine(params, tiny, max_slots=4, max_seq=128,
                      max_queue_depth=n_req + 1).start()
        for _ in range(2)
    ]
    try:
        # warm both paths (compile + thread spin-up) before timing
        engines[0].submit(prompt, max_new=2).wait(30)
        direct = []
        for _ in range(n_req):
            t0 = time.perf_counter()
            h = engines[0].submit(prompt, max_new=max_new)
            h.wait(30)
            direct.append(time.perf_counter() - t0)

        router = TokenRouter(endpoint="bench/fleet")
        for idx, eng in enumerate(engines):
            router.add_replica(idx, eng)
        router.generate(prompt, max_new=2, wait_timeout_s=30)
        routed = []
        for _ in range(n_req):
            t0 = time.perf_counter()
            router.generate(prompt, max_new=max_new, wait_timeout_s=30)
            routed.append(time.perf_counter() - t0)
    finally:
        for eng in engines:
            eng.stop()
    router_added_ms = (pct50(routed) - pct50(direct)) * 1e3

    # -- hot tick -> Serving: annotation write, warm bind, gang ready --
    from odh_kubeflow_tpu.api.core import Container
    from odh_kubeflow_tpu.api.inference import (
        AutoscalingSpec, InferenceEndpoint, ServingSpec,
    )
    from odh_kubeflow_tpu.api.notebook import TPUSpec
    from odh_kubeflow_tpu.cluster import SimCluster
    from odh_kubeflow_tpu.controllers import Config, constants as CC
    from odh_kubeflow_tpu.controllers.inference import (
        endpoint_desired_replicas,
    )
    from odh_kubeflow_tpu.main import build_manager
    from odh_kubeflow_tpu.probe import sim_agent_behavior
    from odh_kubeflow_tpu.runtime.autoscaler import ReplicaAutoscaler

    cluster = SimCluster().start()
    cluster.add_tpu_pool("bench", "v5e", "2x2", slices=4)
    agents = {}
    cluster.add_pod_behavior(sim_agent_behavior(agents, duty=0.9))
    config = Config(
        enable_culling=False, readiness_probe_period_s=0.15,
        serving_loading_window_s=10.0, serving_drain_timeout_s=0.5,
        slo_enabled=False, canary_period_s=0.0,
    )
    mgr = build_manager(cluster.store, config, http_get=cluster.http_get)
    mgr.start()
    try:
        ep = InferenceEndpoint()
        ep.metadata.name = "fleet-bench"
        ep.metadata.namespace = "bench"
        ep.spec.template.spec.containers = [
            Container(name="fleet-bench", image="serve:1")
        ]
        ep.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2")
        ep.spec.serving = ServingSpec(
            max_batch_slots=2, replicas=1,
            autoscaling=AutoscalingSpec(min_replicas=1, max_replicas=2),
        )
        cluster.client.create(ep)

        def serving_replicas():
            got = cluster.client.get(InferenceEndpoint, "bench",
                                     "fleet-bench")
            return got.status.serving_replicas

        def wait(fn, timeout, what):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if fn():
                    return
                time.sleep(0.02)
            raise SystemExit(f"fleet episode: timeout on {what}")

        wait(lambda: serving_replicas() >= 1, 60, "endpoint Serving")
        scaler = ReplicaAutoscaler(
            mgr, period_s=9999.0,
            signals_fn=lambda _ep: {"burn_rate": 10.0, "queue_depth": 99.0,
                                    "slot_occupancy": 1.0},
        )
        t0 = time.monotonic()
        scaler.tick()
        desired = endpoint_desired_replicas(
            cluster.client.get(InferenceEndpoint, "bench", "fleet-bench")
        )
        wait(lambda: serving_replicas() >= desired, 60,
             "autoscaled replica Serving")
        scale_up_reaction_s = time.monotonic() - t0
    finally:
        mgr.stop()
        cluster.stop()

    return {
        "router_added_latency_p50_ms": round(router_added_ms, 3),
        "scale_up_reaction_s": round(scale_up_reaction_s, 3),
        "requests_per_path": n_req,
        "replicas": 2,
        "note": "tiny-model in-process episode: gates structure and "
                "order-of-magnitude, not chip speed",
    }


def bench_accounting():
    """ISSUE 17 chip-time ledger numbers, scripted and deterministic (no
    jax, no hardware): a 180-sim-second fleet episode on SimCluster driven
    through the ChipAccountant on an injected clock, touching every phase
    in the taxonomy (ready / starting / idle-bound / suspended-warm /
    repairing / draining / pool-free / reclaim-churn).

    Two headlines ride this episode (bench/ledger.py):

    - **fleet_utilization** — fraction of accounted chip-seconds in the
      productive phases (ready | draining). The episode script is fixed, so
      this number only moves when the CLASSIFIER moves — a regression means
      the phase mapping started mis-attributing chips.
    - **chip_seconds_per_ready_notebook** — total chip-seconds the notebook
      class consumed (starting/idle/repair overhead included) per notebook
      that reached ready. The end-to-end cost of keeping a notebook served;
      lower is better.

    INVCHECK is armed for the whole episode, so every tick also re-verifies
    the conservation invariant — a double- or zero-attribution fails the
    bench, not just the test suite.
    """
    import os
    from datetime import datetime, timezone

    from odh_kubeflow_tpu.api.core import (
        Container, Node, Pod, ResourceRequirements,
    )
    from odh_kubeflow_tpu.api.job import TPUJob
    from odh_kubeflow_tpu.api.notebook import Notebook
    from odh_kubeflow_tpu.api.notebook.v1beta1 import TPUStatus
    from odh_kubeflow_tpu.cluster import SimCluster
    from odh_kubeflow_tpu.cluster.slicepool import (
        POOL_CLAIMED_BY_ANNOTATION, POOL_PRIORITY_ANNOTATION,
        POOL_STATE_ANNOTATION, POOL_STATE_CLAIMED, POOL_STATE_WARM,
    )
    from odh_kubeflow_tpu.controllers import constants as CC
    from odh_kubeflow_tpu.runtime.accounting import ChipAccountant
    from odh_kubeflow_tpu.tpu import TPU_RESOURCE

    def iso(t):
        return (
            datetime.fromtimestamp(t, tz=timezone.utc)
            .isoformat()
            .replace("+00:00", "Z")
        )

    clk = {"t": 0.0}
    cluster = SimCluster().start()
    prev_invcheck = os.environ.get("INVCHECK")
    os.environ["INVCHECK"] = "1"
    try:
        # 6 v5e 2x2 slices = 6 single-host pools x 4 chips = 24 chips
        cluster.add_tpu_pool("acct", "v5e", "2x2", slices=6)
        acct = ChipAccountant(
            cluster.client, idle_after_s=100.0, clock=lambda: clk["t"]
        )

        def node_of(pool):
            return cluster.client.get(Node, "", f"{pool}-w0")

        def annotate_node(pool, updates):
            node = node_of(pool)
            for k, v in updates.items():
                if v is None:
                    node.metadata.annotations.pop(k, None)
                else:
                    node.metadata.annotations[k] = v
            cluster.client.update(node)

        def bind_pod(name, pool, owner_label, owner):
            pod = Pod()
            pod.metadata.name = name
            pod.metadata.namespace = "bench"
            pod.metadata.labels = {owner_label: owner}
            pod.spec.node_name = f"{pool}-w0"
            pod.spec.containers = [Container(
                name="tpu",
                image="work:1",
                resources=ResourceRequirements(requests={TPU_RESOURCE: "4"}),
            )]
            cluster.client.create(pod)

        def set_notebook(name, **ann):
            nb = cluster.client.get(Notebook, "bench", name)
            for k, v in ann.items():
                key = {
                    "suspend": CC.TPU_SUSPEND_STATE_ANNOTATION,
                    "activity": CC.LAST_ACTIVITY_ANNOTATION,
                }[k]
                nb.metadata.annotations[key] = v
            cluster.client.update(nb)

        def run_until(t_end, step=5.0):
            while clk["t"] < t_end:
                clk["t"] = min(t_end, clk["t"] + step)
                acct.tick()

        # t=0: four mesh-ready notebooks bound to acct-0..3, two free pools
        for i in range(4):
            nb = Notebook()
            nb.metadata.name = f"nb-{i}"
            nb.metadata.namespace = "bench"
            nb.metadata.annotations[CC.LAST_ACTIVITY_ANNOTATION] = iso(0)
            nb.status.tpu = TPUStatus(mesh_ready=True)
            cluster.client.create(nb)
            bind_pod(f"nb-{i}-pod", f"acct-{i}", CC.NOTEBOOK_NAME_LABEL,
                     f"nb-{i}")
        acct.tick()  # baseline
        run_until(60)  # 4x ready, 2x pool-free

        # t=60: nb-3 begins suspending (checkpointing -> draining); nb-0/1
        # stay active, nb-2's kernel goes quiet (idle-bound past t=100)
        set_notebook("nb-3", suspend="checkpointing")
        set_notebook("nb-0", activity=iso(60))
        set_notebook("nb-1", activity=iso(60))
        run_until(80)

        # t=80: nb-3 suspended; its slice returns to the pool WARM and is
        # held on the suspended owner's behalf (suspended-warm)
        cluster.client.delete(Pod, "bench", "nb-3-pod")
        set_notebook("nb-3", suspend="suspended")
        annotate_node("acct-3", {
            POOL_STATE_ANNOTATION: POOL_STATE_WARM,
            POOL_PRIORITY_ANNOTATION: "10",
        })
        run_until(120)

        # t=120: nb-1's host fails silently (repairing); a training job
        # claims pool acct-4 (reclaim-churn: the claim->bind window)
        cluster.fail_node("acct-1-w0")
        annotate_node("acct-4", {
            POOL_STATE_ANNOTATION: POOL_STATE_CLAIMED,
            POOL_CLAIMED_BY_ANNOTATION: "bench/train-a",
        })
        set_notebook("nb-0", activity=iso(120))
        set_notebook("nb-1", activity=iso(120))
        run_until(150)

        # t=150: host healed; the job binds (starting), then runs
        cluster.restore_node("acct-1-w0")
        job = TPUJob()
        job.metadata.name = "train-a"
        job.metadata.namespace = "bench"
        job.metadata.annotations[CC.JOB_STATE_ANNOTATION] = "admitted"
        cluster.client.create(job)
        annotate_node("acct-4", {
            POOL_STATE_ANNOTATION: None,
            POOL_CLAIMED_BY_ANNOTATION: None,
        })
        bind_pod("train-a-pod", "acct-4", CC.JOB_NAME_LABEL, "train-a")
        run_until(165)
        job = cluster.client.get(TPUJob, "bench", "train-a")
        job.metadata.annotations[CC.JOB_STATE_ANNOTATION] = "running"
        cluster.client.update(job)
        set_notebook("nb-0", activity=iso(165))
        set_notebook("nb-1", activity=iso(165))
        run_until(180)

        snap = acct.snapshot()
        cons = acct.conservation()
        notebook_chip_s = acct.chip_seconds(workload_class="notebook")
        ready_notebooks = 4  # all four banked ready time in the script
        return {
            "fleet_utilization": snap["fleet_utilization"],
            "chip_seconds_per_ready_notebook": round(
                notebook_chip_s / ready_notebooks, 3
            ),
            "conservation_residual_ratio": cons["residual_ratio"],
            "physical_chip_seconds": round(
                cons["physical_chip_seconds"], 3
            ),
            "by_phase": snap["chip_seconds"]["by_phase"],
            "by_class": snap["chip_seconds"]["by_class"],
            "phases_observed": len(snap["chip_seconds"]["by_phase"]),
            "ticks": snap["ticks"],
            "note": "scripted 180-sim-second episode on an injected clock; "
                    "INVCHECK armed every tick — numbers move only when the "
                    "classifier moves",
        }
    finally:
        if prev_invcheck is None:
            os.environ.pop("INVCHECK", None)
        else:
            os.environ["INVCHECK"] = prev_invcheck
        cluster.stop()


def bench_serving():
    """Continuous batching vs the static-batch generate() baseline at EQUAL
    batch slots under a mixed-length request stream (ISSUE 9 acceptance:
    goodput >= 1.5x static). Static batching runs every batch to its
    longest member — finished sequences keep burning HBM-bound decode steps
    on slots nobody reads; the serving engine recycles each slot at
    EOS/max-tokens and backfills from the queue, so the same chip does
    strictly more useful tokens per second. TTFT and per-token latency come
    from the live engine (p50/p99 over the episode)."""
    import jax
    import jax.numpy as jnp

    from odh_kubeflow_tpu.models import TransformerConfig, generate, init_params
    from odh_kubeflow_tpu.serving.engine import ServingEngine

    cfg = TransformerConfig(
        vocab=32768,
        d_model=1024,
        n_layers=8,
        n_heads=8,
        d_ff=4096,
        max_seq=2048,
        dtype=jnp.bfloat16,
        use_flash=True,
        remat=False,
    )
    slots, prompt_len, max_seq = 8, 128, 512
    # mixed-length stream: a short-heavy mix (the realistic chat shape) with
    # a long tail — exactly where static batching pays the padding tax
    lengths = [16, 16, 32, 32, 48, 64, 96, 128, 192, 256] * 2
    import random as _random

    order = list(lengths)
    _random.Random(0).shuffle(order)  # arrival order, seeded

    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = jax.random.PRNGKey(1)
    prompts = jax.device_get(
        jax.random.randint(rng, (len(order), prompt_len), 0, cfg.vocab)
    )

    def fetch(x):
        int(jnp.sum(x))  # host fetch = true completion

    # -- static baseline: FIFO batches of `slots`, each run to its longest
    # member (the bench_decode shape, at the same slot count) --
    batches = [
        list(range(i, min(i + slots, len(order))))
        for i in range(0, len(order), slots)
    ]
    # compile warm: one throwaway generate per distinct batch length
    for batch in batches:
        n = max(order[i] for i in batch)
        fetch(generate(params, jnp.asarray(prompts[batch], jnp.int32), cfg,
                       max_new=n, max_seq=max_seq))
    t0 = time.perf_counter()
    for batch in batches:
        n = max(order[i] for i in batch)
        fetch(generate(params, jnp.asarray(prompts[batch], jnp.int32), cfg,
                       max_new=n, max_seq=max_seq))
    static_s = time.perf_counter() - t0
    useful_tokens = sum(order)
    static_goodput = useful_tokens / static_s

    # -- continuous batching: same requests, same slot count, run with the
    # JAXGUARD compile/transfer budgets ARMED (ISSUE 12): the whole bench
    # episode doubles as the compilation-discipline soak — a steady-state
    # retrace or an in-burst host sync fails the bench here, not in a
    # latency graph three PRs later --
    import os

    from odh_kubeflow_tpu.analysis import hotregions
    from odh_kubeflow_tpu.utils import jaxguard

    from odh_kubeflow_tpu.tpu import telemetry as _telemetry
    from odh_kubeflow_tpu.utils import profiler

    jaxguard_prev = os.environ.get("JAXGUARD")
    os.environ["JAXGUARD"] = "1"
    try:
        engine = ServingEngine(params, cfg, max_slots=slots, max_seq=max_seq,
                               max_queue_depth=len(order) + 1, decode_burst=16)
        # compile warm: prefill + one decode step
        warm = engine.submit(list(prompts[0][:prompt_len]), max_new=2)
        while not engine.idle():
            engine.step()
        assert warm.result == "ok"

        handles = []
        step_samples = []  # (wall_s, active_slots) per decode step
        t0 = time.perf_counter()
        for i, n in enumerate(order):
            handles.append(engine.submit(list(prompts[i]), max_new=n))
        steps_since_mem = 0
        while not engine.idle():
            s0 = time.perf_counter()
            active = engine.stats()["active_slots"]
            engine.step()
            if active:
                step_samples.append((time.perf_counter() - s0, active))
            # feed the profiler's HBM watermark every few bursts (the live
            # probe agent does this from its own thread; the bench samples
            # inline so the serving section can report hbm_headroom)
            steps_since_mem += 1
            if steps_since_mem >= 8:
                steps_since_mem = 0
                _telemetry.update_device_memory()
        _telemetry.update_device_memory()
        cb_s = time.perf_counter() - t0
        cb_goodput = sum(len(h.tokens) for h in handles) / cb_s
    finally:
        if jaxguard_prev is None:
            os.environ.pop("JAXGUARD", None)
        else:
            os.environ["JAXGUARD"] = jaxguard_prev

    guard_stats = engine.stats()
    burst_budget = hotregions.get("serving.decode_burst").compile_budget
    assert guard_stats["decode_burst_recompiles"] <= burst_budget, (
        f"decode burst traced {guard_stats['decode_burst_recompiles']}x, "
        f"compile budget {burst_budget} (analysis/hotregions.py) — a "
        "retrace hazard landed in the serving engine"
    )
    assert guard_stats["host_transfers_last_burst"] == 1, (
        f"{guard_stats['host_transfers_last_burst']} host transfers in the "
        "last burst — steady state is exactly ONE batched post-burst drain"
    )

    def pct(xs, p):
        if not xs:
            return None
        xs = sorted(xs)
        return xs[min(len(xs) - 1, int(p * (len(xs) - 1) + 0.5))]

    ttfts = [h.ttft_s for h in handles if h.ttft_s is not None]
    per_token = [dt for dt, _ in step_samples]
    return {
        "continuous_goodput_tokens_per_s": round(cb_goodput),
        "static_batch_goodput_tokens_per_s": round(static_goodput),
        # THE acceptance ratio: >= 1.5x at equal batch slots
        "goodput_vs_static_batch": round(cb_goodput / static_goodput, 3),
        "ttft_p50_ms": round(pct(ttfts, 0.50) * 1e3, 2) if ttfts else None,
        "ttft_p99_ms": round(pct(ttfts, 0.99) * 1e3, 2) if ttfts else None,
        "per_token_p50_ms": (
            round(pct(per_token, 0.50) * 1e3, 2) if per_token else None
        ),
        "per_token_p99_ms": (
            round(pct(per_token, 0.99) * 1e3, 2) if per_token else None
        ),
        "requests": len(order),
        "batch_slots": slots,
        "prompt_len": prompt_len,
        "max_seq": max_seq,
        "output_lengths": "16-256 mixed (short-heavy, seeded shuffle)",
        "mean_slot_occupancy": round(
            sum(a for _, a in step_samples) / (len(step_samples) or 1) / slots,
            3,
        ),
        # ISSUE 12 counters, mined from the JAXGUARD compile/transfer guard
        # (the bench asserts the budgets above — a regression fails here)
        "decode_burst_recompiles": guard_stats["decode_burst_recompiles"],
        "decode_burst_compile_budget": burst_budget,
        "prefill_recompiles": guard_stats["prefill_recompiles"],
        "host_transfers_per_burst": guard_stats["host_transfers_last_burst"],
        # the r12 hot-loop transfer fix: the post-burst drain now pulls all
        # five per-slot outputs in ONE device_get (was 5 host syncs per
        # burst — at decode_burst=16 that's 5 tunnel round trips amortized
        # to 1 per 16 tokens/slot)
        "drain_note": "post-burst drain batched: 1 host sync per burst (was 5)",
        # ISSUE 15: global HBM watermark + headroom mined from the
        # profiler's device-memory feed (null on a backend without
        # memory_stats, e.g. the CPU proxy)
        "hbm_headroom": profiler.hbm_stats(),
        # ISSUE 16: the serving-fleet episode (router tax + autoscale
        # reaction) — its two numbers are declared ledger headlines
        "fleet": _bench_fleet_episode(),
    }


# ---------------------------------------------------------------------------
# Control-plane half (the round-1 benchmark, reported on its own terms)
# ---------------------------------------------------------------------------


def bench_ring_balance():
    """Static ring load-balance tables (VERDICT r4 #8) — no hardware: the
    per-rank block-unit counts from the chunk-id classification the kernels
    switch on (ops/ring_attention.ring_balance_report)."""
    from odh_kubeflow_tpu.ops.ring_attention import ring_balance_report

    out = {}
    for sp in (4, 8):
        cont = ring_balance_report(sp, "contiguous")
        zz = ring_balance_report(sp, "zigzag")
        out[f"sp{sp}"] = {
            "contiguous_per_rank_units": cont["per_rank_total_units"],
            "zigzag_per_rank_units": zz["per_rank_total_units"],
            "contiguous_balance_ratio": round(cont["balance_ratio"], 4),
            "zigzag_balance_ratio": round(zz["balance_ratio"], 4),
            "lockstep_wall_units": {
                "contiguous": cont["lockstep_wall_units"],
                "zigzag": zz["lockstep_wall_units"],
            },
        }
    return out


def bench_flash_block_overhead():
    """The zigzag ring's per-visit unit (flash_block_with_lse pairs + the
    (out, lse) merge) vs the plain fused causal kernel at equal total
    shapes — the single-chip overhead the ring pays for composability
    (VERDICT r4 #8's on-chip half)."""
    import jax
    import jax.numpy as jnp

    from odh_kubeflow_tpu.ops.attention import flash_attention
    from odh_kubeflow_tpu.ops.ring_attention import flash_block_with_lse

    def fetch(x):
        float(jnp.sum(x.astype(jnp.float32)))

    key = jax.random.PRNGKey(0)
    b, s, h, d = 4, 4096, 16, 64
    chunk = s // 2
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (b, s, h, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, s, h, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, s, h, d), jnp.bfloat16)

    import functools

    t_plain = _bench_slope(
        functools.partial(flash_attention, causal=True), (q, k, v), fetch, n2=40
    )

    # one zigzag-style visit over the same tokens: chunk pairs with the
    # (out, lse) merge — (qa: causal on ka) + (qb: full on ka, causal on kb).
    # The carry is the full q, so the loop body depends on the whole visit.
    ka, kb = k[:, :chunk], k[:, chunk:]
    va, vb = v[:, :chunk], v[:, chunk:]

    def merge(o1, l1, o2, l2):
        m = jnp.maximum(l1, l2)
        w1 = jnp.exp(l1 - m)[..., None]
        w2 = jnp.exp(l2 - m)[..., None]
        return (o1 * w1 + o2 * w2) / (w1 + w2)

    def visit(qfull, ka, kb, va, vb):
        qa, qb = qfull[:, :chunk], qfull[:, chunk:]
        o1, _l1 = flash_block_with_lse(qa, ka, va, True, False)
        o2, l2 = flash_block_with_lse(qb, ka, va, False, False)
        o3, l3 = flash_block_with_lse(qb, kb, vb, True, False)
        bot = merge(o2.astype(jnp.float32), l2, o3.astype(jnp.float32), l3)
        return jnp.concatenate(
            [o1.astype(jnp.float32), bot], axis=1
        ).astype(qfull.dtype)

    t_blocks = _bench_slope(visit, (q, ka, kb, va, vb), fetch, n2=40)
    return {
        "shape": f"b{b} s{s} h{h} d{d} (chunk {chunk})",
        "plain_causal_ms": round(t_plain * 1e3, 4),
        "block_visit_ms": round(t_blocks * 1e3, 4),
        "overhead_ratio": round(t_blocks / t_plain, 4),
        "note": "same causal FLOPs: plain = one fused kernel; block visit = "
                "3 chunk kernels (1 full + 2 causal) + (out,lse) merge — "
                "the ring's per-visit decomposition cost on one chip",
    }


READINESS_PHASES = (
    "notebook.ready",
    "webhook.mutate",
    "reconcile.statefulset",
    "reconcile.service",
    "reconcile.route",
    "reconcile.status",
    "kubelet.container.start",
    "probe.first_healthy",
)


def _readiness_phase_breakdown():
    """Per-phase p50 (ms) of the readiness path, mined from the trace buffer:
    for each trace, the FIRST occurrence of each phase span (steady-state
    re-reconciles are not bring-up), then the median across traces."""
    from odh_kubeflow_tpu.utils import tracing

    by_phase: dict = {name: [] for name in READINESS_PHASES}
    seen: set = set()
    for span in tracing.recent_spans():
        key = (span["trace_id"], span["name"])
        if span["name"] not in by_phase or key in seen:
            continue
        seen.add(key)
        by_phase[span["name"]].append(span["duration_ms"])
    return {
        name: {"p50_ms": round(statistics.median(durs), 3), "traces": len(durs)}
        for name, durs in by_phase.items()
        if durs
    }


def _bench_slice_repair(cluster, deadline_s=60.0):
    """One scripted host-preemption episode against a running multi-host
    notebook: report repair MTTR (p50 over slice.repair spans) and the
    interruption-survival rate from the repair counters."""
    from odh_kubeflow_tpu.api.core import Pod
    from odh_kubeflow_tpu.api.notebook import Notebook
    from odh_kubeflow_tpu.controllers import constants as CC
    from odh_kubeflow_tpu.tpu import telemetry
    from odh_kubeflow_tpu.utils import tracing

    interruptions0 = telemetry.slice_interruptions_total.value(cause="HostPreempted")
    repaired0 = telemetry.slice_repairs_total.value(result="repaired")
    failed0 = telemetry.slice_repairs_total.value(result="failed")

    victim_nb = "pod-0"
    pod = cluster.client.get(Pod, "bench", f"{victim_nb}-0")
    victim_node = pod.spec.node_name
    cluster.preempt_node(victim_node, grace_s=0.5)

    deadline = time.monotonic() + deadline_s
    healed = False
    while time.monotonic() < deadline:
        nb = cluster.client.get(Notebook, "bench", victim_nb)
        episode_ran = (
            telemetry.slice_interruptions_total.value(cause="HostPreempted")
            > interruptions0
        )
        if (
            episode_ran
            and CC.TPU_REPAIR_STATE_ANNOTATION not in nb.metadata.annotations
            and nb.status.tpu is not None
            and nb.status.tpu.mesh_ready
        ):
            healed = True
            break
        time.sleep(0.02)
    cluster.restore_node(victim_node)

    mttrs = [
        s["duration_ms"] / 1e3
        for s in tracing.recent_spans(name="slice.repair")
        if s["attributes"].get("result") == "repaired"
    ]
    interruptions = (
        telemetry.slice_interruptions_total.value(cause="HostPreempted")
        - interruptions0
    )
    survived = telemetry.slice_repairs_total.value(result="repaired") - repaired0
    failures = telemetry.slice_repairs_total.value(result="failed") - failed0
    return {
        "episodes": int(interruptions),
        "survived_to_ready": healed,
        "repair_mttr_p50_s": round(statistics.median(mttrs), 4) if mttrs else None,
        "interruption_survival_rate": (
            round(survived / max(1.0, survived + failures), 4)
            if interruptions
            else None
        ),
        "note": "one scripted host preemption against a 4-host v5p notebook: "
        "Degraded -> checkpoint-before-evict -> gang re-placed (spare pool) "
        "-> Ready; MTTR mined from slice.repair trace spans",
    }


def _bench_slo_and_canary(mgr, min_probes: int = 3, wait_s: float = 30.0):
    """Wait for the canary to finish a few probes, then report the SLO
    engine's compliance verdicts and the canary latency percentiles."""
    from odh_kubeflow_tpu.runtime.prober import (
        canary_probe_latency_seconds,
        canary_probes_total,
    )
    from odh_kubeflow_tpu.runtime.slo import slo_compliance_ratio

    deadline = time.monotonic() + wait_s
    while (
        canary_probes_total.sum_matching({}) < min_probes
        and time.monotonic() < deadline
    ):
        time.sleep(0.1)
    if mgr.slo_engine is not None:
        mgr.slo_engine.evaluate()  # one fresh tick so gauges reflect now
    compliance = {
        slo.name: round(slo_compliance_ratio.value(slo=slo.name), 6)
        for slo in (mgr.slo_engine.slos if mgr.slo_engine else ())
        if "readiness" in slo.name
    }
    total = canary_probes_total.sum_matching({})
    ok = canary_probes_total.value(result="ok")
    return {
        "compliance": compliance,
        "canary": {
            "probes": int(total),
            "ok": int(ok),
            "p50_s": canary_probe_latency_seconds.percentile(0.5),
            "p99_s": canary_probe_latency_seconds.percentile(0.99),
        },
    }


def _bench_suspend_resume(notebooks=6, cycles=2, cold_start_s=0.75):
    """Scripted suspend/resume churn episode (ISSUE 7) in its OWN cluster:
    cold creates pay a modeled mesh-formation delay (libtpu init + mesh
    form — the cost a real TPU pod pays on a cold slice), warm-pool binds
    skip it (env staged, mesh pre-formed). Reports the headline
    `resume_vs_cold_create_p50` ratio plus the pool hit ratio over the
    churn."""
    from odh_kubeflow_tpu.api.core import Container, Node, Pod
    from odh_kubeflow_tpu.api.notebook import Notebook, TPUSpec
    from odh_kubeflow_tpu.cluster import SimCluster
    from odh_kubeflow_tpu.cluster.slicepool import (
        slice_pool_hits_total,
        slice_pool_misses_total,
    )
    from odh_kubeflow_tpu.controllers import (
        Config,
        NotebookReconciler,
        ProbeStatusController,
        SuspendResumeController,
        constants as CC,
    )
    from odh_kubeflow_tpu.probe import sim_agent_behavior
    from odh_kubeflow_tpu.runtime import Manager

    config = Config(
        suspend_enabled=True,
        readiness_probe_period_s=0.1,
        suspend_checkpoint_window_s=2.0,
        resume_timeout_s=30.0,
        resume_max_attempts=4,
        # capacity exactly fits the churn: there is no real pressure, so the
        # reclaimer must not misread a busy-process scheduling hiccup as
        # pressure and eat a warm slice mid-measurement
        reclaim_pending_grace_s=5.0,
    )
    cluster = SimCluster().start()
    cluster.add_tpu_pool("warmable", "v5e", "2x2", slices=notebooks)
    agents = {}
    cluster.add_pod_behavior(
        sim_agent_behavior(
            agents,
            duty=0.9,
            cold_start_s=cold_start_s,
            node_lookup=lambda name: cluster.client.get(Node, "", name),
        )
    )
    mgr = Manager(cluster.store)
    NotebookReconciler(mgr, config).setup()
    ProbeStatusController(mgr, config, http_get=cluster.http_get).setup()
    SuspendResumeController(mgr, config, http_get=cluster.http_get).setup()
    mgr.start()

    hits0 = slice_pool_hits_total.value()
    misses0 = slice_pool_misses_total.value()

    def make_nb(name):
        nb = Notebook()
        nb.metadata.name = name
        nb.metadata.namespace = "churn"
        nb.spec.template.spec.containers = [
            Container(name=name, image="jupyter:latest")
        ]
        nb.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2")
        return nb

    def mesh_ready(name):
        nb = cluster.client.get(Notebook, "churn", name)
        return nb.status.tpu is not None and nb.status.tpu.mesh_ready

    def wait(fn, timeout, what):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if fn():
                return
            time.sleep(0.01)
        raise SystemExit(f"suspend/resume episode: timeout on {what}")

    names = [f"churn-{i}" for i in range(notebooks)]
    try:
        # phase A — COLD creates (the baseline the warm pool must beat)
        cold_s = {}
        for name in names:
            t0 = time.monotonic()
            cluster.client.create(make_nb(name))
            wait(lambda n=name: mesh_ready(n), 60, f"{name} cold bring-up")
            cold_s[name] = time.monotonic() - t0

        # phase B — suspend/resume churn
        resume_s = []
        for _ in range(cycles):
            # (re-)wire checkpoint hooks on the CURRENT agent incarnations:
            # each resume spawns a fresh agent, and a hook left on the old
            # one would make every later suspend a hookless window-expiry
            # wait instead of the acked path this episode measures
            for name in names:
                agents[f"{name}-0"].checkpoint_hook = lambda: {"step": 1}
            for name in names:
                cluster.client.patch(
                    Notebook, "churn", name,
                    {"metadata": {"annotations": {
                        CC.STOP_ANNOTATION: "2026-01-01T00:00:00Z",
                        CC.TPU_SUSPEND_STATE_ANNOTATION: "checkpointing",
                    }}},
                )
            for name in names:
                wait(
                    lambda n=name: cluster.client.get(
                        Notebook, "churn", n
                    ).metadata.annotations.get(
                        CC.TPU_SUSPEND_STATE_ANNOTATION
                    ) == "suspended",
                    60, f"{name} suspended",
                )
            # let every drain finish: a resume measured mid-scale-down pays
            # pod-name turnover (old ordinal still terminating), which is a
            # churn-script artifact, not the warm-bind path users hit
            for name in names:
                wait(
                    lambda n=name: not [
                        p for p in cluster.client.list(
                            Pod, namespace="churn",
                            labels={"notebook-name": n},
                        )
                        if not p.metadata.deletion_timestamp
                    ],
                    60, f"{name} drained",
                )
            for name in names:
                t0 = time.monotonic()
                cluster.client.patch(
                    Notebook, "churn", name,
                    {"metadata": {"annotations": {CC.STOP_ANNOTATION: None}}},
                )
                wait(
                    lambda n=name: mesh_ready(n)
                    and not cluster.client.get(
                        Notebook, "churn", n
                    ).metadata.annotations.get(
                        CC.TPU_SUSPEND_STATE_ANNOTATION
                    ),
                    60, f"{name} resume",
                )
                resume_s.append(time.monotonic() - t0)
    finally:
        mgr.stop()
        cluster.stop()

    hits = slice_pool_hits_total.value() - hits0
    misses = slice_pool_misses_total.value() - misses0
    cold_p50 = statistics.median(cold_s.values())
    resume_p50 = statistics.median(resume_s)
    return {
        "resume_vs_cold_create_p50": round(resume_p50 / cold_p50, 4),
        "cold_create_p50_s": round(cold_p50, 4),
        "resume_p50_s": round(resume_p50, 4),
        "resumes": len(resume_s),
        "slice_pool_hit_ratio": round(hits / (hits + misses), 4)
        if hits + misses else None,
        "modeled_cold_mesh_formation_s": cold_start_s,
        "note": "scripted churn: cull->checkpoint->warm-release then "
        "unstop->warm-claim->restore; cold creates pay a modeled libtpu/"
        "mesh-formation delay that warm (env-staged, mesh-formed) slices "
        "skip — the capacity-multiplexing fast path (NotebookOS direction)",
    }


def _bench_batch_contention():
    """Three-way contention episode (ISSUE 10): batch TPUJobs + notebook
    churn + a serving endpoint inside ONE chip budget. Phase A runs the
    jobs alone (the no-contention baseline); phase B adds an endpoint
    pinned Serving and an interactive notebook whose arrival reclaims a
    job's slice (checkpoint-preempt-requeue) and whose suspension hands it
    back warm. Reports the goodput ratio vs baseline and the preemption
    survival rate — 1.0 means every preempted job resumed from a step its
    workload actually acked and still completed."""
    import json as _json

    from odh_kubeflow_tpu.api.core import Container
    from odh_kubeflow_tpu.api.job import TPUJob
    from odh_kubeflow_tpu.api.notebook import Notebook, TPUSpec
    from odh_kubeflow_tpu.cluster import SimCluster
    from odh_kubeflow_tpu.controllers import Config, constants as CC
    from odh_kubeflow_tpu.main import build_manager
    from odh_kubeflow_tpu.probe import sim_agent_behavior
    from odh_kubeflow_tpu.runtime import jobmetrics as JM

    NS = "batch"
    JOBS = ["rl-0", "rl-1"]
    # ~16 cadence checkpoints per job: long enough that the interactive
    # arrival lands mid-run (a job finishing before the reclaim would turn
    # the episode into an idle-warm claim, not a preemption)
    STEPS, STEP_PER_CKPT = 480, 30

    def run_phase(contention):
        cluster = SimCluster().start()
        cluster.add_tpu_pool("v5e", "v5e", "2x2", slices=3)  # 12 chips
        acked = {name: [] for name in JOBS}
        steps = {name: 0 for name in JOBS}

        def http_get(url, timeout=10.0):
            if "/tpu/checkpoint" in url:
                for name in JOBS:
                    if f"{name}-learner" in url:
                        steps[name] += STEP_PER_CKPT
                        acked[name].append(steps[name])
                        return 200, _json.dumps(
                            {"saved": True, "step": steps[name]}
                        ).encode()
                # the churn notebook's suspend checkpoint: ack instantly
                return 200, _json.dumps({"saved": True, "step": 1}).encode()
            return cluster.http_get(url, timeout=timeout)

        config = Config(
            enable_culling=False, suspend_enabled=True,
            readiness_probe_period_s=0.15,
            suspend_checkpoint_window_s=1.0, resume_timeout_s=20.0,
            # budget 16 over 12 physical chips: the fourth workload is
            # ADMITTED demand (oversubscription), so pressure degrades into
            # preemption — a 12 budget would just queue the notebook
            reclaim_pending_grace_s=0.3, chip_budget=16,
            serving_loading_window_s=10.0, serving_drain_timeout_s=0.5,
            job_checkpoint_window_s=2.0, job_requeue_backoff_s=0.2,
            slo_enabled=False, canary_period_s=0.0,
        )
        mgr = build_manager(cluster.store, config, http_get=http_get)
        agents = {}
        cluster.add_pod_behavior(sim_agent_behavior(agents, duty=0.9))
        mgr.start()

        def wait(fn, timeout, what):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if fn():
                    return
                time.sleep(0.02)
            raise SystemExit(f"batch episode: timeout on {what}")

        def job_state(name):
            return cluster.client.get(TPUJob, NS, name) \
                .metadata.annotations.get(CC.JOB_STATE_ANNOTATION, "")

        goodput0 = dict(JM._goodput)
        t0 = time.monotonic()
        try:
            if contention:
                from odh_kubeflow_tpu.api.inference import (
                    InferenceEndpoint, ServingSpec,
                )

                ep = InferenceEndpoint()
                ep.metadata.name = "serve"
                ep.metadata.namespace = NS
                ep.spec.template.spec.containers = [
                    Container(name="serve", image="serve:1")
                ]
                ep.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2")
                ep.spec.serving = ServingSpec(max_batch_slots=4,
                                              max_queue_depth=16)
                cluster.client.create(ep)
                wait(
                    lambda: cluster.client.get(
                        InferenceEndpoint, NS, "serve"
                    ).metadata.annotations.get(
                        CC.INFERENCE_STATE_ANNOTATION
                    ) == "serving",
                    40, "endpoint Serving",
                )

            for name in JOBS:
                job = TPUJob()
                job.metadata.name = name
                job.metadata.namespace = NS
                job.spec.template.spec.containers = [
                    Container(name=name, image="jax:1")
                ]
                job.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2")
                job.spec.steps = STEPS
                job.spec.checkpoint_period_s = 0.4
                cluster.client.create(job)
            for name in JOBS:
                wait(lambda n=name: job_state(n) == "running", 40,
                     f"{name} running")

            if contention:
                # the interactive user arrives: priority 0 > batch -10 —
                # the reclaimer checkpoint-preempts one job for the slice
                nb = Notebook()
                nb.metadata.name = "user"
                nb.metadata.namespace = NS
                nb.spec.template.spec.containers = [
                    Container(name="user", image="jupyter:latest")
                ]
                nb.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2")
                cluster.client.create(nb)
                wait(
                    lambda: any(
                        int(cluster.client.get(TPUJob, NS, n)
                            .metadata.annotations.get(
                                CC.JOB_PREEMPTIONS_ANNOTATION, "0") or 0)
                        for n in JOBS
                    ),
                    30, "a job preempted for the notebook",
                )
                wait(
                    lambda: (lambda got: got.status.tpu is not None
                             and got.status.tpu.mesh_ready)(
                        cluster.client.get(Notebook, NS, "user")),
                    40, "notebook on the reclaimed slice",
                )
                # ...and goes idle: suspend hands the slice back warm, the
                # preempted job warm-claims it and resumes from its step
                cluster.client.patch(Notebook, NS, "user", {"metadata": {
                    "annotations": {
                        CC.STOP_ANNOTATION: "2026-01-01T00:00:00Z",
                        CC.TPU_SUSPEND_STATE_ANNOTATION: "checkpointing",
                    }}})

            # bounded, non-fatal completion wait: a preempted job that
            # never resumes must show up as survival < 1.0, not as a bench
            # error — the survival rate has to be falsifiable
            deadline = time.monotonic() + 90
            final = {}
            while time.monotonic() < deadline and len(final) < len(JOBS):
                for name in JOBS:
                    if name not in final:
                        state = job_state(name)
                        if state in ("succeeded", "failed"):
                            final[name] = state
                time.sleep(0.05)
            elapsed = time.monotonic() - t0

            preempted = survived = 0
            resumes_honest = True
            for name in JOBS:
                job = cluster.client.get(TPUJob, NS, name)
                ann = job.metadata.annotations
                n_preempt = int(
                    ann.get(CC.JOB_PREEMPTIONS_ANNOTATION, "0") or 0
                )
                if n_preempt:
                    preempted += 1
                    if final.get(name) != "succeeded":
                        continue  # did not survive: burns the rate
                    survived += 1
                    resume_step = int(
                        ann.get(CC.JOB_RESUME_STEP_ANNOTATION, "0") or 0
                    )
                    # the resumed-from step must be one the workload ACKED
                    if resume_step not in acked[name]:
                        resumes_honest = False
            incomplete = sorted(set(JOBS) - set(final))
        finally:
            mgr.stop()
            cluster.stop()

        dp = JM._goodput["productive_s"] - goodput0["productive_s"]
        dw = JM._goodput["wall_s"] - goodput0["wall_s"]
        return {
            "goodput_ratio": round(dp / dw, 4) if dw else None,
            "wall_s": round(elapsed, 3),
            "jobs": len(JOBS),
            "preempted": preempted,
            "survival": (survived / preempted) if preempted else None,
            "resumes_from_acked_step": resumes_honest,
            "incomplete": incomplete,
        }

    baseline = run_phase(contention=False)
    contended = run_phase(contention=True)
    survival = contended["survival"]
    return {
        "job_goodput_ratio": contended["goodput_ratio"],
        "job_goodput_ratio_no_contention": baseline["goodput_ratio"],
        "goodput_vs_no_contention": round(
            contended["goodput_ratio"] / baseline["goodput_ratio"], 4
        ) if baseline["goodput_ratio"] and contended["goodput_ratio"]
        else None,
        "preemption_survival_rate": survival,
        "resumes_from_acked_step": contended["resumes_from_acked_step"],
        "preempted_jobs": contended["preempted"],
        "incomplete_jobs": contended["incomplete"],
        "wall_s": {"no_contention": baseline["wall_s"],
                   "contention": contended["wall_s"]},
        "note": "scripted three-way episode: 2 batch jobs + 1 interactive "
        "notebook + 1 serving endpoint, 16-chip budget over 12 "
        "physical; the "
        "notebook's arrival checkpoint-preempts a job (priority -10 < 0), "
        "its suspension hands the slice back warm, the job requeues and "
        "resumes from its acked step",
    }


def bench_control_plane():
    import os

    from odh_kubeflow_tpu.api.core import Container
    from odh_kubeflow_tpu.api.notebook import Notebook, TPUSpec
    from odh_kubeflow_tpu.cluster import SimCluster
    from odh_kubeflow_tpu.controllers import Config
    from odh_kubeflow_tpu.main import build_manager
    from odh_kubeflow_tpu.probe import sim_agent_behavior
    from odh_kubeflow_tpu.runtime import cpprofile
    from odh_kubeflow_tpu.utils import tracing

    tracing.clear()  # this run's traces only
    # CPPROFILE=1 for the storm episode (ISSUE 20): reconcile-cause /
    # cache-scan accounting across the real controller suite plus the
    # takeover decomposition — two ledger headlines ride this
    # (cache_scans_per_reconcile, takeover_relist_share). Scoped to this
    # episode with save/restore, same idiom as bench_accounting's INVCHECK.
    prev_cpprofile = os.environ.get("CPPROFILE")
    os.environ["CPPROFILE"] = "1"
    cpprofile.reset()

    def make_notebook(name, accelerator, topology):
        nb = Notebook()
        nb.metadata.name = name
        nb.metadata.namespace = "bench"
        nb.spec.template.spec.containers = [
            Container(name=name, image="jupyter:latest")
        ]
        nb.spec.tpu = TPUSpec(accelerator=accelerator, topology=topology)
        return nb

    cluster = SimCluster().start()
    # API priority & fairness in front of every request (ISSUE 13): the
    # storm below runs through admission, and the artifact reports
    # shed/queued/p99 wait per priority level
    from odh_kubeflow_tpu.cluster.flowcontrol import FlowController

    flowcontrol = FlowController()
    cluster.store.flowcontrol = flowcontrol
    agents = {}
    cluster.add_pod_behavior(sim_agent_behavior(agents, duty=0.9))
    # +1 spare v5e slice: the black-box canary drives one tiny notebook at a
    # time through the full readiness path and needs a slice of its own
    cluster.add_tpu_pool("v5e", "v5e", "2x2", slices=SINGLE_HOST_NOTEBOOKS + 1)
    # +1 spare v5p slice: the repair episode below needs a same-topology
    # fallback pool for its all-or-nothing gang re-placement
    cluster.add_tpu_pool("v5p", "v5p", "2x2x4", slices=MULTI_HOST_NOTEBOOKS + 1)

    mgr = build_manager(
        cluster.store,
        Config(
            readiness_probe_period_s=0.2,
            # SLO engine on scaled windows (5m -> 6s) so compliance numbers
            # settle within the bench run; canary probing continuously
            slo_window_scale=0.02,
            canary_period_s=1.0,
            canary_timeout_s=30.0,
            canary_accelerator="v5e",
            canary_topology="2x2",
        ),
        http_get=cluster.http_get,
    )
    mgr.start()

    notebooks = [(f"nb-{i}", "v5e", "2x2") for i in range(SINGLE_HOST_NOTEBOOKS)] + [
        (f"pod-{i}", "v5p", "2x2x4") for i in range(MULTI_HOST_NOTEBOOKS)
    ]
    t0 = {}
    try:
        for name, acc, topo in notebooks:
            t0[name] = time.monotonic()
            cluster.client.create(make_notebook(name, acc, topo))

        latencies = {}
        chips_bound = 0
        deadline = time.monotonic() + 120
        pending = {name for name, _, _ in notebooks}
        while pending and time.monotonic() < deadline:
            for name in list(pending):
                nb = cluster.client.get(Notebook, "bench", name)
                if nb.status.tpu and nb.status.tpu.mesh_ready:
                    latencies[name] = time.monotonic() - t0[name]
                    chips_bound += nb.status.tpu.chips_expected
                    pending.discard(name)
            time.sleep(0.005)
        if pending:
            raise SystemExit(f"timeout: {sorted(pending)} never mesh-ready")

        # slice repair episode (ISSUE 4): preempt one host of a multi-host
        # notebook and measure the Degraded -> Ready-again MTTR through the
        # checkpoint-evict-reschedule path, mined from the repair telemetry
        # and slice.repair trace spans
        try:
            slice_repair = _bench_slice_repair(cluster)
        except Exception as e:
            slice_repair = {"error": repr(e)[:300]}

        # SLO verdicts + canary numbers (ISSUE 5): give the black-box prober
        # a few more round trips, then read what the judgement layer says
        # about the storm this bench just ran
        try:
            slo_section = _bench_slo_and_canary(mgr)
        except Exception as e:
            slo_section = {"error": repr(e)[:300]}

        # control-plane profile (ISSUE 20): freeze the episode's cause/scan
        # accounting while both managers are still live (stopping them
        # abandons in-flight takeover trackers). cache_scans_per_reconcile
        # is the fleet-wide flat-cache cost — cached objects walked per
        # reconcile across every controller; takeover_relist_share is the
        # fraction of completed takeover wall-clock spent relisting. Both
        # are the denominators ROADMAP item 5's indexing/fan-out refactor
        # is ledger-gated against; lower is better.
        try:
            cp = cpprofile.snapshot(limit=0)
            total_recon = sum(
                s["reconciles"] for s in cp["controllers"].values()
            )
            total_scanned = sum(
                s["scanned"] for s in cp["controllers"].values()
            )
            completed = [t for t in cp["takeovers"] if t.get("complete")]
            relist_s = sum(t["phases"]["relist"] for t in completed)
            takeover_s = sum(t["total_s"] for t in completed)
            top_scanners = dict(sorted(
                (
                    (name, {
                        "reconciles": s["reconciles"],
                        "scanned": s["scanned"],
                        "used": s["used"],
                        "scans_per_reconcile": s["scans_per_reconcile"],
                        "causes": dict(list(s["causes"].items())[:4]),
                    })
                    for name, s in cp["controllers"].items()
                ),
                key=lambda kv: kv[1]["scanned"], reverse=True,
            )[:5])
            cpprofile_section = {
                "cache_scans_per_reconcile": (
                    round(total_scanned / total_recon, 4)
                    if total_recon else None
                ),
                "takeover_relist_share": (
                    round(relist_s / takeover_s, 4) if takeover_s else None
                ),
                "reconciles": total_recon,
                "objects_scanned": total_scanned,
                "takeovers": completed,
                "top_scanners": top_scanners,
                "note": "storm episode only (CPPROFILE armed for this "
                        "cluster + manager pair); scans_per_reconcile is "
                        "the flat-cache walk cost ROADMAP item 5 targets",
            }
        except Exception as e:
            cpprofile_section = {"error": repr(e)[:300]}
    finally:
        mgr.stop()
        cluster.stop()
        if prev_cpprofile is None:
            os.environ.pop("CPPROFILE", None)
        else:
            os.environ["CPPROFILE"] = prev_cpprofile

    # suspend/resume churn (ISSUE 7): its own cluster, so the modeled cold
    # mesh-formation delay doesn't distort the storm numbers above
    try:
        suspend_resume = _bench_suspend_resume()
    except SystemExit as e:
        suspend_resume = {"error": str(e)}
    except Exception as e:
        suspend_resume = {"error": repr(e)[:300]}

    # batch contention (ISSUE 10): jobs + notebook churn + an endpoint
    # contending inside one chip budget — goodput + preemption survival
    try:
        batch = _bench_batch_contention()
    except SystemExit as e:
        batch = {"error": str(e)}
    except Exception as e:
        batch = {"error": repr(e)[:300]}

    out_slo = {
        "slo_readiness_compliance": slo_section.get("compliance"),
        "canary_probe": slo_section.get("canary"),
    }
    if "error" in slo_section:
        # keep the failure visible (the slice_repair section does the same):
        # nulls alone are indistinguishable from "not yet settled"
        out_slo["slo_error"] = slo_section["error"]
    # the flowcontrol section (ISSUE 13): per-priority-level shed/queued/
    # p99-wait across everything this bench just pushed through admission
    flow_levels = {
        level: {
            "dispatched": stats["dispatched"],
            "shed": stats["rejected"] + stats["timed_out"],
            "queued": stats["queued"],
            "p99_wait_s": stats["p99_wait_s"],
        }
        for level, stats in flowcontrol.summary().items()
    }
    return {
        "slice_repair": slice_repair,
        "suspend_resume": suspend_resume,
        "batch": batch,
        "flowcontrol": flow_levels,
        "cpprofile": cpprofile_section,
        **out_slo,
        "cr_to_mesh_ready_p50_s": round(statistics.median(latencies.values()), 4),
        # where the time goes: per-phase p50 from the connected readiness
        # traces (root notebook.ready = CR submit -> jax.devices ready)
        "readiness_phases": _readiness_phase_breakdown(),
        "p90_s": round(statistics.quantiles(latencies.values(), n=10)[-1], 4),
        "multi_host_p50_s": round(
            statistics.median(
                v for k, v in latencies.items() if k.startswith("pod-")
            ),
            4,
        ),
        "notebooks": len(latencies),
        "chips_bound": chips_bound,
        "note": "in-process sim latency incl. device-visibility readiness gate; "
        "reference publishes no comparable number (SURVEY §6)",
    }


def _stamp_ledger(result):
    """Attach the trajectory ledger + where_time_went to the report (ISSUE
    15). Never costs the artifact: any ledger failure lands as an error
    field inside the block, and an unimportable ledger is skipped."""
    try:
        from bench import ledger
    except Exception as e:  # pragma: no cover - packaging diagnostics
        result["ledger"] = {"error": f"unimportable: {e!r}"[:300]}
        return result
    return ledger.stamp(result)


def main() -> None:
    # Positive-evidence accelerator detection (VERDICT r3 weak #1): round 3's
    # `jax.default_backend() == "tpu"` gate silently skipped every TPU
    # section because the bench host's platform string was "axon" (the
    # dispatch tunnel). Detection now asks for any non-CPU device and the
    # artifact records an explicit skip_reason when the TPU half doesn't run.
    # jax.devices() itself can WEDGE on a dead tunnel, so the probe runs in a
    # daemon thread with its own budget — never block before the (CPU-only)
    # control-plane numbers are out.
    import os
    import threading

    # arm the continuous profiler for the whole run (ISSUE 15): every
    # guarded region/jit and every engine step feeds the where_time_went
    # breakdown the ledger stamps into the report. Respect an explicit
    # PROFILE=0 (overhead A/B runs).
    os.environ.setdefault("PROFILE", "1")

    detail = {"tpu_present": False}

    probe_result = {}

    def _probe():
        from odh_kubeflow_tpu.tpu.detect import accelerator_present

        present, reason = accelerator_present()
        probe_result["present"] = present
        probe_result["reason"] = reason

    probe_t = threading.Thread(target=_probe, daemon=True, name="bench-probe")
    probe_t.start()
    probe_t.join(timeout=300.0)
    if probe_t.is_alive():
        on_tpu = False
        detail["tpu_skip_reason"] = (
            "jax.devices() did not return within 300s (tunnel wedged?)"
        )
    else:
        on_tpu = bool(probe_result.get("present"))
        if not on_tpu:
            detail["tpu_skip_reason"] = probe_result.get("reason") or "unknown"
    detail["tpu_present"] = on_tpu

    # Control plane FIRST (CPU-only, cheap): if the tunnel wedges later, the
    # partial-result line still carries a real p50 (ADVICE r3 #1 — the old
    # order left the watchdog JSON with value: null).
    try:
        detail["control_plane"] = bench_control_plane()
    except SystemExit as e:
        detail["control_plane"] = {"error": str(e)}
    except Exception as e:
        detail["control_plane"] = {"error": repr(e)[:300]}

    # static (hardware-free) ring balance tables — always recorded
    try:
        detail["ring_balance"] = bench_ring_balance()
    except Exception as e:
        detail["ring_balance"] = {"error": repr(e)[:300]}

    # fleet chip-time ledger episode (ISSUE 17) — sim-clocked, always
    # recorded: fleet_utilization + chip_seconds_per_ready_notebook
    try:
        detail["accounting"] = bench_accounting()
    except Exception as e:
        detail["accounting"] = {"error": repr(e)[:300]}

    # the ISSUE 16 serving-fleet episode is CPU-capable (tiny model, sim
    # cluster); on a TPU run bench_serving carries it, on a CPU-only run
    # record it here so router_added_latency_p50_ms / scale_up_reaction_s
    # land in the committed round with non-null vs_prior deltas
    if not on_tpu:
        try:
            detail["serving"] = {"fleet": _bench_fleet_episode()}
        except SystemExit as e:
            detail["serving"] = {"fleet": {"error": str(e)}}
        except Exception as e:
            detail["serving"] = {"fleet": {"error": repr(e)[:300]}}

    # watchdog: the dispatch tunnel occasionally wedges with the main thread
    # blocked inside a C extension call (observed in round 3: trivial ops
    # hang indefinitely). Signals can't preempt a thread stuck in C, so a
    # DAEMON THREAD owns the deadline: on expiry it prints whatever has been
    # measured so far as the one required JSON line and hard-exits — the
    # driver gets a partial result instead of a timeout.
    watchdog_fired = threading.Event()

    def _watchdog(budget_s: float) -> None:
        if watchdog_fired.wait(timeout=budget_s):
            return  # disarmed
        detail["watchdog"] = (
            f"TPU sections exceeded {budget_s:.0f}s (tunnel wedged?); "
            "partial results emitted"
        )
        cp = detail.get("control_plane", {})
        print(json.dumps(_stamp_ledger({
            "metric": "notebook_cr_to_slice_ready_p50",
            "value": cp.get("cr_to_mesh_ready_p50_s"),
            "unit": "s",
            "vs_baseline": 1.0,
            "detail": detail,
        })), flush=True)
        os._exit(0)

    kernels = train = None
    if on_tpu:
        threading.Thread(
            target=_watchdog, args=(2100.0,), daemon=True, name="bench-watchdog"
        ).start()
        # Headline sections first (kernels -> train -> decode), expensive
        # secondary sections after, each gated on a SOFT budget so the
        # artifact finishes normally with explicit skips instead of dying in
        # the watchdog's partial-result path when compiles run long.
        t0 = time.monotonic()
        soft_budget_s = 1500.0

        def run_section(name, fn, optional=False):
            if optional and time.monotonic() - t0 > soft_budget_s:
                detail[name] = {
                    "skipped": f"soft budget {soft_budget_s:.0f}s exceeded"
                }
                return None
            try:
                detail[name] = out = fn()
                return out
            except Exception as e:  # pragma: no cover - hardware diagnostics
                detail[name] = {"error": repr(e)[:300]}
                return None

        kernels = run_section("kernels", bench_kernels)
        train = run_section("train_step", bench_train_step)
        run_section("decode", bench_decode)
        run_section("moe_train_step", bench_moe_train_step, optional=True)
        run_section("serving", bench_serving, optional=True)
        run_section("decode_long_cache", bench_decode_long_cache, optional=True)
        run_section("attention_memory", bench_attention_memory, optional=True)
        run_section("flash_block_overhead", bench_flash_block_overhead,
                    optional=True)
        watchdog_fired.set()  # disarm

    if on_tpu and kernels and train and "error" not in detail.get("train_step", {}):
        result = {
            "metric": "train_step_tokens_per_s_v5e1",
            "value": train["tokens_per_s"],
            "unit": "tokens/s",
            # no comparable published framework number exists; the kernel
            # speedup is reported under its own honest name, never as the
            # headline metric's baseline ratio
            "vs_baseline": 1.0,
            "flash_vs_xla_attention_4k": kernels["flash_vs_xla_attention_4k"],
            "kernel_mfu": kernels["kernel_mfu"],
            "detail": detail,
        }
    else:
        cp = detail.get("control_plane", {})
        result = {
            "metric": "notebook_cr_to_slice_ready_p50",
            "value": cp.get("cr_to_mesh_ready_p50_s"),
            "unit": "s",
            "vs_baseline": 1.0,  # no comparable published number exists
            "detail": detail,
        }
    print(json.dumps(_stamp_ledger(result)))


if __name__ == "__main__":
    main()
