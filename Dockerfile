# Controller-manager image (the reference builds with ubi9/go-toolset from the
# components/ context — notebook-controller/Dockerfile:1-30; this build is a
# Python manager plus an optional C++ runtime core compiled at image build).
FROM python:3.11-slim AS builder
RUN apt-get update && apt-get install -y --no-install-recommends g++ make \
    && rm -rf /var/lib/apt/lists/*
WORKDIR /src
COPY pyproject.toml ./
COPY odh_kubeflow_tpu ./odh_kubeflow_tpu
COPY native ./native
RUN make -C native 2>/dev/null || true
RUN pip install --no-cache-dir .

FROM python:3.11-slim
RUN useradd --uid 1001 --create-home controller
COPY --from=builder /usr/local/lib/python3.11/site-packages /usr/local/lib/python3.11/site-packages
USER 1001
ENTRYPOINT ["python", "-m", "odh_kubeflow_tpu.main"]
