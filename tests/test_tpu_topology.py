"""Slice planner: topology parsing, host math, GKE selectors, env contract."""
import pytest

from odh_kubeflow_tpu.apimachinery import InvalidError
from odh_kubeflow_tpu.tpu import (
    GKE_TPU_ACCELERATOR_LABEL,
    GKE_TPU_TOPOLOGY_LABEL,
    host_bounds,
    plan_slice,
    tpu_env,
)


def test_v5p_32_shape():
    # BASELINE target config: multi-host v5p-32 (16 chips, 4 hosts x 4 chips)
    s = plan_slice("v5p", topology="2x2x4")
    assert s.chips == 16
    assert s.hosts == 4
    assert s.chips_per_host == 4
    assert s.multi_host
    assert s.accelerator_type == "v5p-32"
    assert s.node_selector() == {
        GKE_TPU_ACCELERATOR_LABEL: "tpu-v5p-slice",
        GKE_TPU_TOPOLOGY_LABEL: "2x2x4",
    }


def test_v5e_4_single_host():
    # BASELINE target config: single-host v5e-4
    s = plan_slice("v5e", topology="2x2")
    assert s.chips == 4 and s.hosts == 1 and not s.multi_host
    assert s.chips_per_host == 4


def test_v5e_8_single_host_machine():
    s = plan_slice("v5e", topology="2x4")
    assert s.chips == 8 and s.hosts == 1  # ct5lp-hightpu-8t shape


def test_v5e_16_multi_host():
    # BASELINE target config: PyTorch/XLA on v5e-16
    s = plan_slice("v5e", topology="4x4")
    assert s.chips == 16 and s.hosts == 4 and s.chips_per_host == 4


def test_chips_requests_smallest_topology():
    s = plan_slice("v5p", chips=10)
    assert s.chips >= 10
    assert s.hosts == s.chips // 4


def test_default_is_one_host():
    s = plan_slice("v5e")
    assert s.hosts == 1 and s.chips == 4


def test_invalid_inputs():
    with pytest.raises(InvalidError):
        plan_slice("v7x")
    with pytest.raises(InvalidError):
        plan_slice("v5p", topology="2x2")  # v5p is 3D
    with pytest.raises(InvalidError):
        plan_slice("v5e", topology="2x2x2")  # v5e is 2D
    with pytest.raises(InvalidError):
        plan_slice("v5p", topology="banana")
    with pytest.raises(InvalidError):
        plan_slice("v5p", topology="2x2x2", chips=8)
    with pytest.raises(InvalidError):
        plan_slice("v5e", chips=100000)


def test_host_bounds_partition_topology():
    s = plan_slice("v5p", topology="2x2x4")
    assert host_bounds(s) == "1,1,4"  # 4 hosts of 2x2x1 chips stacked in z


def test_env_contract_multi_host():
    s = plan_slice("v5p", topology="2x2x4")
    env = {e["name"]: e["value"] for e in tpu_env(s, "nb", "nb", "user")}
    assert env["JAX_PLATFORMS"] == "tpu"
    assert env["JAX_NUM_PROCESSES"] == "4"
    assert env["JAX_COORDINATOR_ADDRESS"] == "nb-0.nb.user.svc.cluster.local:8476"
    hostnames = env["TPU_WORKER_HOSTNAMES"].split(",")
    assert len(hostnames) == 4
    assert hostnames[3] == "nb-3.nb.user.svc.cluster.local"
    assert env["NB_TPU_CHIPS_EXPECTED"] == "16"


def test_env_contract_pytorch():
    s = plan_slice("v5e", topology="4x4")
    env = {e["name"]: e["value"] for e in tpu_env(s, "nb", "nb", "u", runtime="pytorch-xla")}
    assert env["PJRT_DEVICE"] == "TPU"
    assert "JAX_PLATFORMS" not in env
