"""Operator-lint + racecheck contract tests (ISSUE 3).

Every static checker is proven BOTH ways: a fixture snippet it must flag and
a clean twin it must pass — a checker that cannot tell the two apart is
either blind or crying wolf. The runtime half gets the determinism proofs:
a two-thread lock-order inversion raises every run (no interleaving
required), re-entrant Lock acquisition raises instead of deadlocking, and
the cache write barrier raises on mutation but launders through deepcopy.

Finally the package-level acceptance gate: the full analysis pass over
odh_kubeflow_tpu/ must report ZERO unsuppressed findings — the same
invariant ci/analysis.sh enforces.
"""
import copy
import threading

import pytest

from odh_kubeflow_tpu.analysis import run_analysis, run_on_source
from odh_kubeflow_tpu.analysis.checkers.cache_mutation import CacheMutationChecker
from odh_kubeflow_tpu.analysis.checkers.conventions import (
    AnnotationConventionChecker,
    MetricConventionChecker,
)
from odh_kubeflow_tpu.analysis.checkers.exceptions import SwallowedExceptionChecker
from odh_kubeflow_tpu.analysis.checkers.lock_discipline import (
    LockDisciplineChecker,
    LockOrderChecker,
)
from odh_kubeflow_tpu.analysis.checkers.jaxlint import (
    DonationDisciplineChecker,
    HostTransferChecker,
    PsumAxisChecker,
    RetraceHazardChecker,
)
from odh_kubeflow_tpu.analysis.checkers.deploylint import (
    CrdSchemaDriftChecker,
    EnvContractChecker,
    FlowSchemaCoverageChecker,
    RbacCoverageChecker,
    make_deploylint_checkers,
)
from odh_kubeflow_tpu.analysis.checkers.machine_conformance import (
    MachineConformanceChecker,
)
from odh_kubeflow_tpu.analysis.framework import (
    collect_pragmas,
    parse_pragma_allowlist,
    pragma_budget_violations,
    render_pragma_allowlist,
)
from odh_kubeflow_tpu.analysis.metric_rules import check_metric, check_registry
from odh_kubeflow_tpu.controllers.config import EnvKnob
from odh_kubeflow_tpu.utils import racecheck

pytestmark = pytest.mark.analysis


@pytest.fixture(autouse=True)
def _reset_racecheck_graph():
    yield
    racecheck.reset()


def checks_of(findings):
    return {f.check for f in findings}


# ---------------------------------------------------------------------------
# cache-mutation
# ---------------------------------------------------------------------------

CACHE_MUTATION_BAD = '''
class C:
    def f(self, key):
        obj = self._cache.get(key)
        obj["metadata"]["labels"]["stale"] = "true"
'''

CACHE_MUTATION_BAD_LOOP = '''
class C:
    def f(self):
        for o in self._cache.values():
            o.setdefault("status", {})
'''

CACHE_MUTATION_CLEAN = '''
import copy
class C:
    def f(self, key):
        obj = copy.deepcopy(self._cache.get(key))
        obj["metadata"]["labels"]["stale"] = "true"
    def g(self, key):
        obj = self._cache.get(key)
        obj = copy.deepcopy(obj)
        obj.update({"a": 1})
    def reads_only(self, key):
        obj = self._cache.get(key)
        return obj.get("metadata", {}).get("name")
'''


def test_cache_mutation_flags_inplace_write():
    findings = run_on_source(CACHE_MUTATION_BAD, [CacheMutationChecker()])
    assert checks_of(findings) == {"cache-mutation"}
    assert "deepcopy" in findings[0].message


def test_cache_mutation_flags_loop_over_cache_values():
    findings = run_on_source(CACHE_MUTATION_BAD_LOOP, [CacheMutationChecker()])
    assert checks_of(findings) == {"cache-mutation"}
    assert "setdefault" in findings[0].message


def test_cache_mutation_passes_after_deepcopy():
    assert run_on_source(CACHE_MUTATION_CLEAN, [CacheMutationChecker()]) == []


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------

SLEEP_UNDER_LOCK = '''
import threading, time
lock = threading.Lock()
def f():
    with lock:
        time.sleep(0.1)
'''

NETWORK_UNDER_LOCK = '''
import threading, urllib.request
class C:
    def __init__(self):
        self._lock = threading.Lock()
    def f(self, url):
        with self._lock:
            return urllib.request.urlopen(url)
'''

CALLBACK_UNDER_LOCK = '''
import threading
class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._handlers = []
    def fire(self, ev):
        with self._lock:
            for handler in self._handlers:
                handler(ev)
'''

REENTRANT_LOCK = '''
import threading
class C:
    def __init__(self):
        self._lock = threading.Lock()
    def outer(self):
        with self._lock:
            self.inner()
    def inner(self):
        with self._lock:
            pass
'''

DISCIPLINE_CLEAN = '''
import threading, time, urllib.request
class C:
    def __init__(self):
        self._lock = threading.RLock()
        self._handlers = []
    def fire(self, ev):
        with self._lock:
            handlers = list(self._handlers)
        for handler in handlers:
            handler(ev)
    def outer(self):
        with self._lock:
            self.inner()          # RLock: re-entry is legal
    def inner(self):
        with self._lock:
            pass
    def slow(self, url):
        time.sleep(0.1)           # outside any lock
        return urllib.request.urlopen(url)
'''


def test_lock_discipline_flags_sleep():
    findings = run_on_source(SLEEP_UNDER_LOCK, [LockDisciplineChecker()])
    assert checks_of(findings) == {"lock-discipline"}
    assert "time.sleep" in findings[0].message


def test_lock_discipline_flags_network_io():
    findings = run_on_source(NETWORK_UNDER_LOCK, [LockDisciplineChecker()])
    assert checks_of(findings) == {"lock-discipline"}
    assert "blocking I/O" in findings[0].message


def test_lock_discipline_flags_callback_dispatch():
    findings = run_on_source(CALLBACK_UNDER_LOCK, [LockDisciplineChecker()])
    assert checks_of(findings) == {"lock-discipline"}
    assert "callback" in findings[0].message


def test_lock_discipline_flags_reentrant_plain_lock():
    findings = run_on_source(REENTRANT_LOCK, [LockDisciplineChecker()])
    assert checks_of(findings) == {"lock-discipline"}
    assert "re-acquires" in findings[0].message


def test_lock_discipline_passes_clean_patterns():
    assert run_on_source(DISCIPLINE_CLEAN, [LockDisciplineChecker()]) == []


# ---------------------------------------------------------------------------
# lock-order (static cycle)
# ---------------------------------------------------------------------------

LOCK_ORDER_CYCLE = '''
import threading
a_lock = threading.Lock()
b_lock = threading.Lock()
def f():
    with a_lock:
        with b_lock:
            pass
def g():
    with b_lock:
        with a_lock:
            pass
'''

LOCK_ORDER_CLEAN = '''
import threading
a_lock = threading.Lock()
b_lock = threading.Lock()
def f():
    with a_lock:
        with b_lock:
            pass
def g():
    with a_lock:
        with b_lock:
            pass
'''


def test_lock_order_flags_static_inversion():
    findings = run_on_source(LOCK_ORDER_CYCLE, [LockOrderChecker()])
    assert checks_of(findings) == {"lock-order"}
    assert "ABBA" in findings[0].message


def test_lock_order_passes_consistent_order():
    assert run_on_source(LOCK_ORDER_CLEAN, [LockOrderChecker()]) == []


# ---------------------------------------------------------------------------
# swallowed-exception
# ---------------------------------------------------------------------------

SWALLOW_BARE = '''
def reconcile(req):
    try:
        work()
    except:
        pass
'''

SWALLOW_BLIND = '''
def reconcile(req):
    try:
        work()
    except Exception:
        pass
'''

SWALLOW_CLEAN = '''
import logging
log = logging.getLogger(__name__)
def reconcile(req):
    try:
        work()
    except Exception as e:
        log.warning("work failed: %s", e)
    try:
        terminals = probe()
    except Exception:
        terminals = []   # fallback assignment is a recorded decision
    return terminals
'''


def test_swallowed_exception_flags_bare_except():
    findings = run_on_source(SWALLOW_BARE, [SwallowedExceptionChecker()])
    assert checks_of(findings) == {"swallowed-exception"}
    assert "bare" in findings[0].message


def test_swallowed_exception_flags_blind_pass():
    findings = run_on_source(SWALLOW_BLIND, [SwallowedExceptionChecker()])
    assert checks_of(findings) == {"swallowed-exception"}


def test_swallowed_exception_passes_logged_and_fallback():
    assert run_on_source(SWALLOW_CLEAN, [SwallowedExceptionChecker()]) == []


SWALLOW_RECONCILE_OUTSIDE_SCOPED_DIRS = '''
def reconcile(req):
    try:
        work()
    except Exception:
        pass

def helper():
    try:
        work()
    except Exception:
        pass
'''


def test_swallowed_exception_covers_reconcile_functions_anywhere():
    # runtime/ is not a scoped dir, but reconcile* functions are reconcile
    # paths wherever they live; the non-reconcile helper stays out of scope
    findings = run_on_source(
        SWALLOW_RECONCILE_OUTSIDE_SCOPED_DIRS,
        [SwallowedExceptionChecker()],
        path="odh_kubeflow_tpu/runtime/somemodule.py",
    )
    assert len(findings) == 1
    assert findings[0].line == 5


# ---------------------------------------------------------------------------
# metric / annotation conventions
# ---------------------------------------------------------------------------

METRIC_BAD = '''
def register(registry):
    registry.counter("requests_count", "Requests seen")          # no _total
    registry.gauge("queue depth", "Items queued")                # bad charset
    registry.counter("retries_total", "")                        # empty help
    registry.histogram("lat_seconds", "Latency", labels=("le",)) # reserved
'''

METRIC_CLEAN = '''
def register(registry):
    registry.counter("requests_total", "Requests seen")
    registry.gauge("queue_depth", "Items queued")
    registry.histogram("lat_seconds", "Latency", labels=("verb",))
'''

ANNOTATION_BAD = '''
def stamp(meta):
    meta.annotations["notebooks.opendatahub.io/update-pending"] = "true"
'''

ANNOTATION_CLEAN = '''
from odh_kubeflow_tpu.controllers import constants as C
def stamp(meta):
    meta.annotations[C.UPDATE_PENDING_ANNOTATION] = "true"
'''


def test_metric_convention_flags_all_four_rules():
    findings = run_on_source(METRIC_BAD, [MetricConventionChecker()])
    messages = " | ".join(f.message for f in findings)
    assert "_total" in messages
    assert "invalid metric name" in messages
    assert "empty help" in messages
    assert "'le'" in messages


def test_metric_convention_passes_compliant_names():
    assert run_on_source(METRIC_CLEAN, [MetricConventionChecker()]) == []


def test_metric_convention_checks_positional_labels():
    src = 'def r(registry):\n    registry.gauge("depth", "Items", ("le",))\n'
    findings = run_on_source(src, [MetricConventionChecker()])
    assert any("'le'" in f.message for f in findings)


def test_annotation_convention_flags_inline_key():
    findings = run_on_source(ANNOTATION_BAD, [AnnotationConventionChecker()])
    assert checks_of(findings) == {"annotation-convention"}
    assert "constants.py" in findings[0].message


def test_annotation_convention_passes_constant_reference():
    assert run_on_source(ANNOTATION_CLEAN, [AnnotationConventionChecker()]) == []


# ---------------------------------------------------------------------------
# machine-conformance (ISSUE 8: the state-machine write contract)
# ---------------------------------------------------------------------------

MACHINE_ROGUE_WRITER = '''
from . import constants as C
def reconcile(self, nb):
    self._patch_annotations(nb, {C.TPU_SUSPEND_STATE_ANNOTATION: "suspended"})
'''

MACHINE_UNDECLARED_STATE = '''
from . import constants as C
def _begin_resume(self, nb):
    self._patch_annotations(nb, {C.TPU_SUSPEND_STATE_ANNOTATION: "warming-up"})
'''

MACHINE_UNDECLARED_TRANSITION = '''
from . import constants as C
def _fail_resume(self, nb):
    self._patch_annotations(nb, {C.TPU_SUSPEND_STATE_ANNOTATION: "suspended"})
'''

# the culler's real contract: the checkpointing stamp rides the SAME patch
# as the stop annotation — both its declared transitions, nothing else
MACHINE_CLEAN_CULLER = '''
from . import constants as C
from ..apimachinery import now_rfc3339
class R:
    def reconcile(self, req):
        updates = {}
        updates[C.STOP_ANNOTATION] = now_rfc3339()
        updates[C.TPU_SUSPEND_STATE_ANNOTATION] = "checkpointing"
        self._patch_annotations(nb, updates)
'''


def test_machine_conformance_flags_non_owning_writer():
    findings = run_on_source(
        MACHINE_ROGUE_WRITER, [MachineConformanceChecker()],
        path="odh_kubeflow_tpu/controllers/rogue.py",
    )
    assert any("not a declared writer" in f.message for f in findings)
    assert all(f.check == "machine-conformance" for f in findings)


def test_machine_conformance_flags_undeclared_state():
    findings = run_on_source(
        MACHINE_UNDECLARED_STATE, [MachineConformanceChecker()],
        path="odh_kubeflow_tpu/controllers/suspend.py",
    )
    assert any("undeclared state 'warming-up'" in f.message for f in findings)


def test_machine_conformance_flags_drifted_transition():
    # a write the spec knows nothing about: suspended out of _fail_resume
    findings = run_on_source(
        MACHINE_UNDECLARED_TRANSITION, [MachineConformanceChecker()],
        path="odh_kubeflow_tpu/controllers/suspend.py",
    )
    assert any(
        "is not declared" in f.message and "_fail_resume" in f.message
        for f in findings
    )


def test_machine_conformance_passes_clean_culler_twin():
    assert run_on_source(
        MACHINE_CLEAN_CULLER, [MachineConformanceChecker()],
        path="odh_kubeflow_tpu/controllers/culling.py",
    ) == []


def test_machine_conformance_reports_spec_drift_against_real_modules(tmp_path):
    # an owner module that no longer implements a declared transition:
    # scanning it (by its real basename) must surface the other drift
    # direction — the spec says _begin_resume writes resuming, nobody does
    mod = tmp_path / "suspend.py"
    mod.write_text(MACHINE_UNDECLARED_TRANSITION)
    findings = run_analysis([str(mod)], checkers=[MachineConformanceChecker()])
    assert any(
        "declared transition" in f.message
        and "_begin_resume has no matching write" in f.message
        for f in findings
    )


def test_repair_owned_conditions_drift_both_directions(tmp_path):
    conditions = tmp_path / "conditions.py"
    conditions.write_text(
        "from . import constants as C\n"
        "REPAIR_OWNED_CONDITIONS = (\n"
        "    C.TPU_DEGRADED_CONDITION,\n"
        "    C.SLO_DEGRADED_CONDITION,\n"
        ")\n"
    )
    repair = tmp_path / "slice_repair.py"
    repair.write_text(
        "from . import constants as C\n"
        "def _enter(self, nb):\n"
        "    write_condition(c, r, nb, C.TPU_HEALTHY_CONDITION, 'True')\n"
        "    write_condition(c, r, nb, C.TPU_DEGRADED_CONDITION, 'True')\n"
    )
    findings = run_analysis(
        [str(conditions), str(repair)], checkers=[MachineConformanceChecker()]
    )
    messages = " | ".join(f.message for f in findings)
    # written but not preserved: the mirror will stomp it
    assert "TPU_HEALTHY_CONDITION is written" in messages
    # preserved but never written: a dead entry
    assert "SLO_DEGRADED_CONDITION is never passed" in messages


def test_real_tree_conditions_and_machines_are_in_sync():
    # the package-level pass runs the full drift checks against the real
    # controllers (owners + conditions.py all in the scan set) — part of
    # the zero-findings gate, asserted here with the checker isolated so a
    # failure names the drift rather than a wall of unrelated findings
    import pathlib

    import odh_kubeflow_tpu

    pkg = pathlib.Path(odh_kubeflow_tpu.__file__).parent
    findings = run_analysis([str(pkg)], checkers=[MachineConformanceChecker()])
    assert findings == [], "\n".join(str(f) for f in findings)


# ---------------------------------------------------------------------------
# dead annotation constants (annotation-convention finish pass)
# ---------------------------------------------------------------------------


def test_dead_annotation_constant_flagged(tmp_path):
    pkg = tmp_path / "controllers"
    pkg.mkdir()
    (pkg / "constants.py").write_text(
        'LIVE_ANNOTATION = "notebooks.tpu.kubeflow.org/live"\n'
        'DEAD_ANNOTATION = "notebooks.tpu.kubeflow.org/dead"\n'
    )
    (pkg / "reader.py").write_text(
        "from . import constants as C\n"
        "def f(nb):\n"
        "    return nb.metadata.annotations.get(C.LIVE_ANNOTATION)\n"
    )
    findings = run_analysis(
        [str(pkg)], checkers=[AnnotationConventionChecker()]
    )
    assert len(findings) == 1
    assert "dead annotation constant DEAD_ANNOTATION" in findings[0].message


def test_dead_annotation_constant_passes_when_read(tmp_path):
    pkg = tmp_path / "controllers"
    pkg.mkdir()
    (pkg / "constants.py").write_text(
        'LIVE_ANNOTATION = "notebooks.tpu.kubeflow.org/live"\n'
    )
    (pkg / "reader.py").write_text(
        "from . import constants as C\n"
        "def f(nb):\n"
        "    return nb.metadata.annotations.get(C.LIVE_ANNOTATION)\n"
    )
    assert run_analysis(
        [str(pkg)], checkers=[AnnotationConventionChecker()]
    ) == []


# ---------------------------------------------------------------------------
# pragma budget gate (ci/analysis.sh + ci/pragma_allowlist.txt)
# ---------------------------------------------------------------------------


def test_pragma_budget_collection_and_gate(tmp_path):
    mod = tmp_path / "m.py"
    mod.write_text(
        "x = 1  # lint: disable=lock-discipline\n"
        "y = 2  # lint: disable=lock-discipline\n"
        "z = 3  # lint: disable=cache-mutation\n"
    )
    budget = collect_pragmas([str(mod)])
    assert budget == {
        (str(mod), "lock-discipline"): 2,
        (str(mod), "cache-mutation"): 1,
    }
    allowlist = parse_pragma_allowlist(render_pragma_allowlist(budget))
    assert allowlist == budget
    assert pragma_budget_violations(budget, allowlist) == []
    # one new unreviewed pragma of an ALREADY-allowlisted check still fails
    mod.write_text(mod.read_text() + "w = 4  # lint: disable=cache-mutation\n")
    grown = collect_pragmas([str(mod)])
    problems = pragma_budget_violations(grown, allowlist)
    assert len(problems) == 1 and "cache-mutation" in problems[0]
    # shrinkage passes (stale allowlist is nagged elsewhere, not fatal)
    assert pragma_budget_violations({}, allowlist) == []


def test_committed_pragma_allowlist_matches_the_tree():
    import pathlib

    import odh_kubeflow_tpu

    pkg = pathlib.Path(odh_kubeflow_tpu.__file__).parent
    repo = pkg.parent
    allowlist = parse_pragma_allowlist(
        (repo / "ci" / "pragma_allowlist.txt").read_text()
    )
    budget = collect_pragmas([str(pkg)])
    # paths in the allowlist are repo-relative; collection from an absolute
    # path yields absolute — normalize to relative-to-repo for comparison
    normalized = {
        (str(pathlib.Path(path).resolve().relative_to(repo.resolve())), check): n
        for (path, check), n in budget.items()
    }
    assert pragma_budget_violations(normalized, allowlist) == [], (
        "unreviewed `# lint: disable` pragmas — regenerate "
        "ci/pragma_allowlist.txt after review"
    )


# ---------------------------------------------------------------------------
# pragma suppression
# ---------------------------------------------------------------------------

def test_pragma_suppresses_on_the_flagged_line():
    src = SWALLOW_BLIND.replace(
        "except Exception:", "except Exception:  # lint: disable=swallowed-exception"
    )
    assert run_on_source(src, [SwallowedExceptionChecker()]) == []


def test_pragma_all_and_file_scope():
    src = "# lint: disable-file=swallowed-exception\n" + SWALLOW_BARE
    assert run_on_source(src, [SwallowedExceptionChecker()]) == []
    src2 = SWALLOW_BARE.replace("except:", "except:  # lint: disable=all")
    assert run_on_source(src2, [SwallowedExceptionChecker()]) == []


def test_pragma_for_other_check_does_not_suppress():
    src = SWALLOW_BLIND.replace(
        "except Exception:", "except Exception:  # lint: disable=cache-mutation"
    )
    findings = run_on_source(src, [SwallowedExceptionChecker()])
    assert checks_of(findings) == {"swallowed-exception"}


def test_pragma_inside_string_literal_is_inert():
    # pragmas are COMMENT tokens; the same text inside a string/docstring
    # (log template, embedded fixture) must not arm a suppression
    src = (
        '"""docstring with # lint: disable-file=all inside"""\n'
        "def reconcile(req):\n"
        '    text = "# lint: disable=all"\n'
        "    try:\n"
        "        work(text)\n"
        "    except:\n"
        "        pass\n"
    )
    findings = run_on_source(src, [SwallowedExceptionChecker()])
    assert checks_of(findings) == {"swallowed-exception"}


# ---------------------------------------------------------------------------
# jaxlint (ISSUE 12): retrace-hazard
# ---------------------------------------------------------------------------

RETRACE_LOOP_BAD = '''
import jax

def run(fs, xs):
    for f in fs:
        g = jax.jit(f)
        g(xs)
'''

RETRACE_IIFE_BAD = '''
import jax

def call(f, x):
    return jax.jit(f)(x)
'''

RETRACE_LAMBDA_BAD = '''
import jax

def call(x):
    f = jax.jit(lambda t: t + 1)
    return f(x)
'''

RETRACE_STATIC_BAD = '''
import jax
from functools import partial

@partial(jax.jit, static_argnames=("n",))
def f(x, n):
    return x[:n]

@partial(jax.jit, static_argnums=(1,))
def g(x, opts):
    return x

def caller(x):
    n = len(x)
    out = f(x, n)
    return g(out, [1, 2])
'''

RETRACE_CLEAN = '''
import jax
from functools import partial

@partial(jax.jit, static_argnames=("n",))
def f(x, n):
    return x[:n]

def caller(x):
    return f(x, 4)
'''


def test_retrace_hazard_flags_jit_in_loop():
    findings = run_on_source(RETRACE_LOOP_BAD, [RetraceHazardChecker()])
    assert checks_of(findings) == {"retrace-hazard"}
    assert any("loop" in f.message for f in findings)


def test_retrace_hazard_flags_immediately_invoked_jit():
    findings = run_on_source(RETRACE_IIFE_BAD, [RetraceHazardChecker()])
    assert checks_of(findings) == {"retrace-hazard"}
    assert any("per call" in f.message for f in findings)


def test_retrace_hazard_flags_jit_over_lambda():
    findings = run_on_source(RETRACE_LAMBDA_BAD, [RetraceHazardChecker()])
    assert checks_of(findings) == {"retrace-hazard"}
    assert any("lambda" in f.message for f in findings)


def test_retrace_hazard_flags_static_arg_hazards():
    findings = run_on_source(RETRACE_STATIC_BAD, [RetraceHazardChecker()])
    assert checks_of(findings) == {"retrace-hazard"}
    messages = " | ".join(f.message for f in findings)
    assert "shape-derived" in messages  # len(x) fed to static n
    assert "non-hashable" in messages  # [1, 2] fed to static opts


def test_retrace_hazard_passes_clean_twin():
    assert run_on_source(RETRACE_CLEAN, [RetraceHazardChecker()]) == []


# ---------------------------------------------------------------------------
# jaxlint: host-transfer (hot regions from analysis/hotregions.py)
# ---------------------------------------------------------------------------

ENGINE_PATH = "odh_kubeflow_tpu/serving/engine.py"

HOST_TRANSFER_BAD = '''
import jax
import jax.numpy as jnp
import numpy as np

class ServingEngine:
    def step(self):
        v = self._latest()
        if jnp.sum(v) > 0:
            pass
        return v.item()

    def _latest(self):
        x = jax.device_get(self._buf)
        return np.asarray(x)
'''

HOST_TRANSFER_CLEAN = '''
import jax

class ServingEngine:
    def step(self):
        out = self._burst()
        drained = jax.device_get(out)  # lint: disable=host-transfer
        return drained

    def _burst(self):
        return self._fn(self._buf)

class Reporter:
    def outside_hot_region(self):
        return float(jax.device_get(self._x)[0])
'''


def test_host_transfer_flags_sync_surfaces_in_hot_region():
    findings = run_on_source(
        HOST_TRANSFER_BAD, [HostTransferChecker()], path=ENGINE_PATH
    )
    assert checks_of(findings) == {"host-transfer"}
    messages = " | ".join(f.message for f in findings)
    assert ".item()" in messages
    assert "device_get" in messages  # in _latest, REACHED from step
    assert "np.asarray" in messages
    assert "branching on a device value" in messages


def test_host_transfer_pragma_and_reachability_scope():
    # the pragma'd intentional drain is suppressed; Reporter is not
    # reachable from any declared hot root, so its transfer is legal
    assert run_on_source(
        HOST_TRANSFER_CLEAN, [HostTransferChecker()], path=ENGINE_PATH
    ) == []


def test_host_transfer_silent_outside_registered_modules():
    # same ugly source, but the module is not a registered hot region
    assert run_on_source(
        HOST_TRANSFER_BAD, [HostTransferChecker()], path="odh/other.py"
    ) == []


# ---------------------------------------------------------------------------
# jaxlint: donation-discipline
# ---------------------------------------------------------------------------

DONATION_MISSING_BAD = '''
import jax
from jax import lax

@jax.jit
def write(cache, new):
    for buf in cache:
        buf = lax.dynamic_update_slice(buf, new, (0, 0))
    return cache
'''

DONATION_READ_AFTER_BAD = '''
import jax
from functools import partial

@partial(jax.jit, donate_argnums=(0,))
def step(state, x):
    return state + x

def loop(state, xs):
    out = step(state, xs)
    return state + out
'''

DONATION_CLEAN = '''
import jax
from functools import partial
from jax import lax

@partial(jax.jit, donate_argnums=(0,))
def write(cache, new):
    out = []
    for buf in cache:
        out.append(lax.dynamic_update_slice(buf, new, (0, 0)))
    return out

def loop(state, xs):
    state = step(state, xs)
    return state

@partial(jax.jit, donate_argnums=(0,))
def step(state, x):
    return state + x
'''


def test_donation_discipline_flags_update_without_donation():
    findings = run_on_source(DONATION_MISSING_BAD, [DonationDisciplineChecker()])
    assert checks_of(findings) == {"donation-discipline"}
    assert any("without donate_argnums" in f.message for f in findings)


def test_donation_discipline_flags_read_after_donation():
    findings = run_on_source(DONATION_READ_AFTER_BAD, [DonationDisciplineChecker()])
    assert checks_of(findings) == {"donation-discipline"}
    assert any("read after being donated" in f.message for f in findings)


def test_donation_discipline_passes_clean_twin():
    assert run_on_source(DONATION_CLEAN, [DonationDisciplineChecker()]) == []


# ---------------------------------------------------------------------------
# jaxlint: psum-axis (cross-module finish() pass)
# ---------------------------------------------------------------------------

PSUM_BAD = '''
from jax import lax

AXES = ("dp", "tp")

def f(x):
    return lax.psum(x, "sp")
'''

PSUM_CLEAN = '''
from jax import lax

AXES = ("dp", "tp")

def f(x):
    return lax.psum(x, "dp")

def g(x, axis_name="tp"):
    return lax.pmean(x, axis_name)
'''

PSUM_NO_DECLARATION = '''
from jax import lax

def f(x):
    return lax.psum(x, "anything")
'''


def test_psum_axis_flags_undeclared_axis():
    findings = run_on_source(PSUM_BAD, [PsumAxisChecker()])
    assert checks_of(findings) == {"psum-axis"}
    assert any("'sp'" in f.message for f in findings)


def test_psum_axis_passes_declared_axes_including_defaults():
    assert run_on_source(PSUM_CLEAN, [PsumAxisChecker()]) == []


def test_psum_axis_silent_without_any_declaration():
    # no mesh axes declared anywhere in the scanned set: no basis to judge
    assert run_on_source(PSUM_NO_DECLARATION, [PsumAxisChecker()]) == []


# ---------------------------------------------------------------------------
# deploylint (ISSUE 14): fixture twins for the deployment-surface family.
# Paths matter here — rbac-coverage only attributes manager modules, and the
# generator/flowcontrol/main fixtures arm their checkers by path.
# ---------------------------------------------------------------------------

MANAGER_PATH = "odh_kubeflow_tpu/controllers/fixture.py"

RBAC_BAD = '''
class R:
    def reconcile(self):
        ns = Namespace()
        self.client.create(ns)
'''

RBAC_CLEAN = '''
class R:
    def reconcile(self):
        cm = self.client.get(ConfigMap, "ns", "n")
        self.client.update(cm)
'''


@pytest.mark.deploylint
def test_rbac_coverage_flags_ungranted_verb_and_passes_clean_twin():
    findings = run_on_source(
        RBAC_BAD, [RbacCoverageChecker()], path=MANAGER_PATH
    )
    assert checks_of(findings) == {"rbac-coverage"}
    assert "Namespace" in findings[0].message and "create" in findings[0].message
    assert run_on_source(
        RBAC_CLEAN, [RbacCoverageChecker()], path=MANAGER_PATH
    ) == []


@pytest.mark.deploylint
def test_rbac_coverage_only_attributes_manager_modules():
    # the same ungranted call in a sim-actor module carries another identity
    assert run_on_source(
        RBAC_BAD, [RbacCoverageChecker()],
        path="odh_kubeflow_tpu/cluster/kubelet.py",
    ) == []


@pytest.mark.deploylint
def test_rbac_coverage_flags_stale_rule_and_surface_clears_it():
    def stale_findings(surface):
        checker = RbacCoverageChecker()
        checker.rbac_override = {("", "namespaces"): frozenset({"delete"})}
        checker.force_stale = True
        checker.surface = surface
        # no client traffic at all: the granted rule is exercised by nothing
        return run_on_source("x = 1", [checker], path=MANAGER_PATH)

    findings = stale_findings(None)
    assert len(findings) == 1 and "stale RBAC" in findings[0].message
    # a runtime surface artifact proving the rule IS exercised clears it
    assert stale_findings({("notebook", "delete", "Namespace", "")}) == []


CRDGEN_PATH = "odh_kubeflow_tpu/deploy/crdgen.py"


@pytest.mark.deploylint
def test_crd_schema_drift_passes_the_committed_tree():
    checker = CrdSchemaDriftChecker()
    assert run_on_source("", [checker], path=CRDGEN_PATH) == []


@pytest.mark.deploylint
def test_crd_schema_drift_flags_a_doctored_manifest(tmp_path):
    import pathlib

    import yaml

    import odh_kubeflow_tpu

    committed = (
        pathlib.Path(odh_kubeflow_tpu.__file__).parent.parent
        / "deploy" / "base" / "manifests.yaml"
    )
    docs = list(yaml.safe_load_all(committed.read_text()))
    for doc in docs:
        if (
            isinstance(doc, dict)
            and doc.get("kind") == "CustomResourceDefinition"
            and doc["metadata"]["name"].startswith("notebooks.")
        ):
            doc["spec"]["scope"] = "Cluster"
    doctored = tmp_path / "manifests.yaml"
    doctored.write_text(yaml.safe_dump_all(docs))

    checker = CrdSchemaDriftChecker()
    checker.manifests_path = str(doctored)
    findings = run_on_source("", [checker], path=CRDGEN_PATH)
    assert findings and "drifted" in findings[0].message
    assert "spec.scope" in findings[0].message


@pytest.mark.deploylint
def test_crd_schema_drift_flags_a_missing_committed_tree(tmp_path):
    checker = CrdSchemaDriftChecker()
    checker.manifests_path = str(tmp_path / "nope.yaml")
    findings = run_on_source("", [checker], path=CRDGEN_PATH)
    assert findings and "missing" in findings[0].message


ENV_BAD = '''
import os
token = os.environ.get("UNDECLARED_TOKEN", "")
'''

ENV_PRAGMA = '''
import os
token = os.environ.get("UNDECLARED_TOKEN", "")  # lint: disable=env-contract
'''


@pytest.mark.deploylint
def test_env_contract_flags_undeclared_read_and_passes_declared_twin():
    checker = EnvContractChecker()
    checker.declared_override = {}
    findings = run_on_source(ENV_BAD, [checker])
    assert checks_of(findings) == {"env-contract"}
    assert "UNDECLARED_TOKEN" in findings[0].message

    declared = EnvContractChecker()
    declared.declared_override = {
        "UNDECLARED_TOKEN": EnvKnob("UNDECLARED_TOKEN", "", "fixture", "doc")
    }
    assert run_on_source(ENV_BAD, [declared]) == []


@pytest.mark.deploylint
def test_env_contract_pragma_suppresses_like_every_checker():
    checker = EnvContractChecker()
    checker.declared_override = {}
    assert run_on_source(ENV_PRAGMA, [checker]) == []


@pytest.mark.deploylint
def test_env_contract_flags_dead_knob_and_manifest_drift():
    checker = EnvContractChecker()
    checker.declared_override = {
        "GHOST_KNOB": EnvKnob("GHOST_KNOB", "", "nobody", "doc"),
        "SHIPPED_KNOB": EnvKnob(
            "SHIPPED_KNOB", "", "nobody", "doc", manifest=True
        ),
    }
    checker.manifest_names_override = {"ORPHAN_ENV"}
    checker.force_finish = True
    messages = [f.message for f in run_on_source("x = 1", [checker])]
    assert any("dead knob" in m and "GHOST_KNOB" in m for m in messages)
    assert any("manifest=True" in m and "SHIPPED_KNOB" in m for m in messages)
    assert any("ORPHAN_ENV" in m and "does not declare" in m for m in messages)


FLOW_BAD = '''
def serve(client):
    with flow_context("totally-unknown-flow"):
        client.list(Notebook)
'''

FLOW_CLEAN = '''
def serve(client):
    with flow_context("notebook"):
        client.list(Notebook)
'''


@pytest.mark.deploylint
def test_flow_schema_coverage_flags_default_classification():
    findings = run_on_source(FLOW_BAD, [FlowSchemaCoverageChecker()])
    assert checks_of(findings) == {"flow-schema-coverage"}
    assert "default PriorityLevel" in findings[0].message
    assert run_on_source(FLOW_CLEAN, [FlowSchemaCoverageChecker()]) == []


@pytest.mark.deploylint
def test_flow_schema_coverage_flags_declared_flow_nothing_enters():
    from odh_kubeflow_tpu.analysis.framework import ModuleInfo

    decl = 'SCHEMAS = (FlowSchema("fixture", "system", flows=("ghost-flow",)),)'
    checker = FlowSchemaCoverageChecker()
    m = ModuleInfo.parse(
        "odh_kubeflow_tpu/cluster/flowcontrol.py", source=decl
    )
    assert list(checker.check(m)) == []
    findings = list(checker.finish())
    assert findings and "ghost-flow" in findings[0].message

    # the twin: a second module entering the flow clears the finding
    entered = FlowSchemaCoverageChecker()
    assert list(entered.check(ModuleInfo.parse(
        "odh_kubeflow_tpu/cluster/flowcontrol.py",
        source='S = (FlowSchema("fixture", "system", flows=("notebook",)),)',
    ))) == []
    assert list(entered.check(ModuleInfo.parse(MANAGER_PATH, source=FLOW_CLEAN))) == []
    assert list(entered.finish()) == []


@pytest.mark.deploylint
def test_flow_schema_coverage_checks_webhook_paths_both_ways():
    served_unregistered = FlowSchemaCoverageChecker()
    served_unregistered.webhook_paths_override = {"/mutate-notebook-v1"}
    findings = run_on_source(
        'server.register("/mutate-bogus-v1", handler)\n',
        [served_unregistered],
    )
    assert findings and "never call it" in findings[0].message

    declared_unserved = FlowSchemaCoverageChecker()
    declared_unserved.webhook_paths_override = {"/mutate-notebook-v1"}
    findings = run_on_source(
        "x = 1", [declared_unserved], path="odh_kubeflow_tpu/main.py"
    )
    assert findings and "fail closed" in findings[0].message

    clean = FlowSchemaCoverageChecker()
    clean.webhook_paths_override = {"/mutate-notebook-v1"}
    assert run_on_source(
        'server.register("/mutate-notebook-v1", handler)\n',
        [clean],
        path="odh_kubeflow_tpu/main.py",
    ) == []


@pytest.mark.deploylint
def test_deploylint_family_is_clean_on_the_real_package():
    """The ci/analysis.sh --deploy acceptance bar, as a pytest gate."""
    import pathlib

    import odh_kubeflow_tpu

    pkg = pathlib.Path(odh_kubeflow_tpu.__file__).parent
    findings = run_analysis([str(pkg)], checkers=make_deploylint_checkers())
    assert findings == [], "\n".join(str(f) for f in findings)


# ---------------------------------------------------------------------------
# shared metric rules (the metrics_lint.sh delegation target)
# ---------------------------------------------------------------------------

def test_check_metric_rules():
    assert check_metric("foo_total", "counter", "help") == []
    assert any("_total" in v for v in check_metric("foo", "counter", "help"))
    assert any("invalid metric name" in v for v in check_metric("a b", "gauge", "x"))
    assert any("empty help" in v for v in check_metric("x_total", "counter", " "))
    assert any("le" in v for v in check_metric("h", "histogram", "x", ["le"]))


def test_check_registry_on_live_global_registry():
    from odh_kubeflow_tpu.runtime.metrics import global_registry

    assert check_registry(global_registry) == []


# ---------------------------------------------------------------------------
# package acceptance gate: zero unsuppressed findings on the real tree
# ---------------------------------------------------------------------------

def test_full_package_has_zero_unsuppressed_findings():
    # resolve from the package location so the gate is real from any cwd
    import pathlib

    import odh_kubeflow_tpu

    pkg = pathlib.Path(odh_kubeflow_tpu.__file__).parent
    findings = run_analysis([str(pkg)])
    assert findings == [], "\n".join(str(f) for f in findings)


def test_run_analysis_refuses_to_scan_nothing():
    with pytest.raises(FileNotFoundError):
        run_analysis(["/nonexistent/typo/path"])


# ---------------------------------------------------------------------------
# racecheck: deterministic lock-order inversion
# ---------------------------------------------------------------------------

def test_two_thread_lock_order_inversion_raises_deterministically():
    graph = racecheck.OrderGraph()
    a = racecheck.RaceCheckLock("A", graph=graph)
    b = racecheck.RaceCheckLock("B", graph=graph)

    def order_ab():
        with a:
            with b:
                pass

    t1 = threading.Thread(target=order_ab)
    t1.start()
    t1.join()

    errors = []

    def order_ba():
        try:
            with b:
                with a:
                    pass
        except racecheck.LockOrderError as e:
            errors.append(e)

    t2 = threading.Thread(target=order_ba)
    t2.start()
    t2.join()

    # no contention, no timing window: the inversion raises because the
    # GRAPH remembers thread 1's order, not because the threads interleaved
    assert len(errors) == 1
    assert "ABBA" in str(errors[0])
    assert "'A'" in str(errors[0]) and "'B'" in str(errors[0])


def test_consistent_order_never_raises():
    graph = racecheck.OrderGraph()
    a = racecheck.RaceCheckLock("A", graph=graph)
    b = racecheck.RaceCheckLock("B", graph=graph)
    for _ in range(3):
        with a:
            with b:
                pass


def test_reentrant_plain_lock_raises_instead_of_deadlocking():
    graph = racecheck.OrderGraph()
    a = racecheck.RaceCheckLock("A", graph=graph)
    with a:
        with pytest.raises(racecheck.LockOrderError, match="re-entrant"):
            a.acquire()


def test_reentrant_rlock_is_legal():
    graph = racecheck.OrderGraph()
    a = racecheck.RaceCheckLock("A", reentrant=True, graph=graph)
    with a:
        with a:
            pass


def test_factories_return_plain_locks_when_disabled(monkeypatch):
    monkeypatch.delenv("RACECHECK", raising=False)
    assert not isinstance(racecheck.make_lock("x"), racecheck.RaceCheckLock)
    assert not isinstance(racecheck.make_rlock("x"), racecheck.RaceCheckLock)
    monkeypatch.setenv("RACECHECK", "1")
    assert isinstance(racecheck.make_lock("x"), racecheck.RaceCheckLock)


# ---------------------------------------------------------------------------
# racecheck: cache write barrier
# ---------------------------------------------------------------------------

def test_guard_dict_raises_on_every_mutator(monkeypatch):
    monkeypatch.setenv("RACECHECK", "1")
    obj = racecheck.guard_cache_object(
        {"metadata": {"labels": {"a": "1"}}, "items": [{"x": 1}]}, "Kind/ns/n"
    )
    # reads are native dict/list semantics
    assert obj["metadata"]["labels"]["a"] == "1"
    assert isinstance(obj, dict) and isinstance(obj["items"], list)
    import json

    json.dumps(obj)  # serializable like plain data
    for mutate in [
        lambda: obj.__setitem__("k", "v"),
        lambda: obj["metadata"].update({"k": "v"}),
        lambda: obj["metadata"]["labels"].pop("a"),
        lambda: obj["metadata"]["labels"].setdefault("b", "2"),
        lambda: obj["items"].append({}),
        lambda: obj["items"][0].clear(),
    ]:
        with pytest.raises(racecheck.CacheMutationError):
            mutate()


def test_guard_deepcopy_launders_to_mutable(monkeypatch):
    monkeypatch.setenv("RACECHECK", "1")
    obj = racecheck.guard_cache_object({"metadata": {"labels": {"a": "1"}}}, "k")
    clean = copy.deepcopy(obj)
    assert type(clean) is dict
    assert type(clean["metadata"]) is dict
    clean["metadata"]["labels"]["a"] = "2"  # no raise
    assert obj["metadata"]["labels"]["a"] == "1"


def test_guard_is_identity_when_disabled(monkeypatch):
    monkeypatch.delenv("RACECHECK", raising=False)
    d = {"a": 1}
    assert racecheck.guard_cache_object(d, "k") is d


# ---------------------------------------------------------------------------
# racecheck wired into the informer path
# ---------------------------------------------------------------------------

def test_informer_cache_reads_are_guarded_under_racecheck(monkeypatch):
    monkeypatch.setenv("RACECHECK", "1")
    from odh_kubeflow_tpu.cluster.store import Store
    from odh_kubeflow_tpu.runtime.informer import Informer

    store = Store()
    store.create_raw(
        {
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {"name": "cm", "namespace": "ns"},
            "data": {"a": "1"},
        }
    )
    inf = Informer(store, "v1", "ConfigMap")
    inf.start()
    assert inf.synced.wait(5)
    try:
        obj = inf.get("ns", "cm")
        assert obj["data"]["a"] == "1"
        with pytest.raises(racecheck.CacheMutationError):
            obj["data"]["a"] = "2"
        listed = inf.list(namespace="ns")
        assert len(listed) == 1
        with pytest.raises(racecheck.CacheMutationError):
            listed[0]["data"].clear()
        # handler-delivered objects are cache-owned too
        seen = []
        inf.add_handler(lambda t, o, old: seen.append(o))
        with pytest.raises(racecheck.CacheMutationError):
            seen[0].setdefault("status", {})
        # the sanctioned path: deepcopy, then mutate freely
        mine = copy.deepcopy(obj)
        mine["data"]["a"] = "2"
        assert inf.get("ns", "cm")["data"]["a"] == "1"
    finally:
        inf.stop()


def test_informer_reads_stay_plain_without_racecheck(monkeypatch):
    monkeypatch.delenv("RACECHECK", raising=False)
    from odh_kubeflow_tpu.cluster.store import Store
    from odh_kubeflow_tpu.runtime.informer import Informer

    store = Store()
    store.create_raw(
        {
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {"name": "cm", "namespace": "ns"},
            "data": {"a": "1"},
        }
    )
    inf = Informer(store, "v1", "ConfigMap")
    inf.start()
    assert inf.synced.wait(5)
    try:
        obj = inf.get("ns", "cm")
        obj["data"]["a"] = "2"  # deep copy: mutation is invisible to the cache
        assert inf.get("ns", "cm")["data"]["a"] == "1"
    finally:
        inf.stop()
