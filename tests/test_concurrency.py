"""Concurrency stress: the -race-detector analog the reference never had
(SURVEY §4: no -race in any Makefile). Hammers the store's multi-writer
paths — optimistic concurrency + retry_on_conflict is the contract that
keeps the reference's annotation state machine safe; these tests prove ours
holds under real thread contention, on whichever backend is active."""
import threading
import time

import pytest

from odh_kubeflow_tpu.api.core import ConfigMap
from odh_kubeflow_tpu.api.notebook import Notebook
from odh_kubeflow_tpu.apimachinery import ConflictError, NotFoundError
from odh_kubeflow_tpu.cluster import Client, Store
from odh_kubeflow_tpu.cluster.client import retry_on_conflict

WRITERS = 8
ROUNDS = 25


@pytest.fixture(params=["python", "native"])
def client(request):
    if request.param == "native":
        from odh_kubeflow_tpu._native import ensure_built, load

        if not (ensure_built() and load()):
            pytest.skip("libnbstore.so unavailable")
    return Client(Store(backend=request.param))


def test_concurrent_annotation_writers_lose_nothing(client):
    """Every writer's annotations land despite constant conflicts — the
    invariant behind last-activity/stop/finalizer multi-writer sites."""
    nb = Notebook()
    nb.metadata.name = "contended"
    nb.metadata.namespace = "ns"
    client.create(nb)
    errors = []

    def writer(i):
        try:
            for r in range(ROUNDS):
                def mutate():
                    cur = client.get(Notebook, "ns", "contended")
                    cur.metadata.annotations[f"writer-{i}/round-{r}"] = "x"
                    client.update(cur)

                # default steps=5 is load-sensitive here: with 8 writers in
                # flight, one thread losing 5 straight GET->update races is
                # plausible on a busy box; the invariant under test (no
                # write lost) does not depend on the budget
                retry_on_conflict(mutate, steps=8)
        except Exception as e:  # pragma: no cover - failure reporting
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(WRITERS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    final = client.get(Notebook, "ns", "contended")
    assert len(final.metadata.annotations) == WRITERS * ROUNDS


def test_conflict_actually_fires_under_contention(client):
    """The guarantee is meaningful only if stale writes really are rejected."""
    nb = Notebook()
    nb.metadata.name = "stale"
    nb.metadata.namespace = "ns"
    client.create(nb)
    first = client.get(Notebook, "ns", "stale")
    second = client.get(Notebook, "ns", "stale")
    first.metadata.annotations["a"] = "1"
    client.update(first)
    second.metadata.annotations["b"] = "2"
    with pytest.raises(ConflictError):
        client.update(second)


def test_concurrent_create_delete_churn_stays_consistent(client):
    """Creators/deleters race on overlapping names; the store must never
    corrupt: survivors readable, casualties NotFound, no duplicates."""
    stop = time.monotonic() + 2.0
    errors = []

    def churn(i):
        n = 0
        try:
            while time.monotonic() < stop:
                name = f"cm-{i}-{n % 5}"
                cm = ConfigMap()
                cm.metadata.name = name
                cm.metadata.namespace = "ns"
                cm.data = {"n": str(n)}
                try:
                    client.create(cm)
                except Exception:
                    pass
                try:
                    client.delete(ConfigMap, "ns", name)
                except NotFoundError:
                    pass
                n += 1
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=churn, args=(i,)) for i in range(WRITERS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    listed = client.list(ConfigMap, namespace="ns")
    names = [o.metadata.name for o in listed]
    assert len(names) == len(set(names)), "duplicate objects after churn"
    for o in listed:
        assert client.get(ConfigMap, "ns", o.metadata.name).data["n"] == o.data["n"]


def test_watch_stream_has_no_gaps_under_writes(client):
    """A watcher must see a coherent ADDED/MODIFIED/DELETED sequence per key
    (level-triggered reconcile correctness depends on this)."""
    store = client.store
    w = store.watch("v1", "ConfigMap", namespace="ns", send_initial=False)
    done = threading.Event()
    seen = []

    def consume():
        while True:
            ev = w.get(timeout=0.2)
            if ev is not None:
                seen.append((ev.type, ev.object["metadata"]["name"]))
            elif done.is_set():
                return

    consumer = threading.Thread(target=consume)
    consumer.start()
    for i in range(20):
        cm = ConfigMap()
        cm.metadata.name = f"w-{i}"
        cm.metadata.namespace = "ns"
        client.create(cm)
        got = client.get(ConfigMap, "ns", f"w-{i}")
        got.data = {"k": "v"}
        client.update(got)
        client.delete(ConfigMap, "ns", f"w-{i}")
    time.sleep(0.3)
    done.set()
    consumer.join()
    per_key = {}
    for typ, name in seen:
        per_key.setdefault(name, []).append(typ)
    assert len(per_key) == 20
    for name, seq in per_key.items():
        assert seq == ["ADDED", "MODIFIED", "DELETED"], (name, seq)
