"""Parallel sim-kubelet pod bring-up (ISSUE 13 satellite): N pods across M
nodes must reach Ready in roughly the longest per-node startup chain, not
the serial sum of every pod's ready_after — the LOADTEST_r05 serial wall.

The kubelet runs `workers` reconcile workers and caps concurrent startups at
`max_starting_per_node` (the container runtime's parallel image-pull
budget); a throttled pod's startup clock does NOT run while it waits for a
slot."""
import time

import pytest

from odh_kubeflow_tpu.api.core import Container, Pod
from odh_kubeflow_tpu.cluster import SimCluster
from odh_kubeflow_tpu.cluster.kubelet import PodDecision

NS = "bringup"
READY_AFTER = 0.3


def mk_bound_pod(name, node):
    """A pod pre-bound to a node: the kubelet picks it up directly, no
    scheduler involvement."""
    pod = Pod()
    pod.metadata.name = name
    pod.metadata.namespace = NS
    pod.spec.containers = [Container(name=name, image="jax:1")]
    pod.spec.node_name = node
    return pod


def all_ready(cluster, names):
    for name in names:
        pod = cluster.client.get(Pod, NS, name)
        if not (pod.status.phase == "Running" and pod.is_ready()):
            return False
    return True


def test_fanout_beats_serial_sum():
    cluster = SimCluster().start()
    try:
        cluster.add_pod_behavior(
            lambda pod: PodDecision(ready_after=READY_AFTER)
            if pod.metadata.namespace == NS
            else None
        )
        nodes = ["node-a", "node-b", "node-c"]
        names = [f"p-{i}" for i in range(24)]
        serial_sum = len(names) * READY_AFTER  # 7.2s if bring-up were serial
        t0 = time.monotonic()
        for i, name in enumerate(names):
            cluster.client.create(mk_bound_pod(name, nodes[i % len(nodes)]))
        deadline = t0 + serial_sum
        while time.monotonic() < deadline:
            if all_ready(cluster, names):
                break
            time.sleep(0.02)
        elapsed = time.monotonic() - t0
        assert all_ready(cluster, names), f"pods not all ready after {elapsed:.1f}s"
        # 8 pods per node / max_starting_per_node=4 -> 2 startup waves per
        # node, nodes in parallel: ~2*READY_AFTER plus scheduling slack.
        # Anything near serial_sum means the fan-out regressed.
        kubelet = cluster.kubelet
        waves_per_node = -(-(len(names) // len(nodes)) // kubelet.max_starting_per_node)
        expected = waves_per_node * READY_AFTER
        assert elapsed < serial_sum / 2, (
            f"bring-up took {elapsed:.2f}s (serial sum {serial_sum:.1f}s, "
            f"expected ~{expected:.1f}s): parallel fan-out regressed"
        )
    finally:
        cluster.stop()


def test_per_node_start_budget_holds_clock():
    """More pods than the per-node budget on ONE node: total time is the
    number of waves times ready_after — proof the queued pods' clocks were
    NOT running while they waited (otherwise all would be ready after
    ~ready_after)."""
    cluster = SimCluster().start()
    try:
        cluster.add_pod_behavior(
            lambda pod: PodDecision(ready_after=READY_AFTER)
            if pod.metadata.namespace == NS
            else None
        )
        budget = cluster.kubelet.max_starting_per_node
        names = [f"q-{i}" for i in range(2 * budget)]
        t0 = time.monotonic()
        for name in names:
            cluster.client.create(mk_bound_pod(name, "solo-node"))
        while time.monotonic() - t0 < 10:
            if all_ready(cluster, names):
                break
            time.sleep(0.02)
        elapsed = time.monotonic() - t0
        assert all_ready(cluster, names)
        # two full waves: the second wave's clocks only started once the
        # first wave freed its slots
        assert elapsed >= 2 * READY_AFTER - 0.05, (
            f"{elapsed:.2f}s: throttled pods' startup clocks ran while queued"
        )
    finally:
        cluster.stop()
