"""KV-cache autoregressive decoding (models/decode.py)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from odh_kubeflow_tpu.models import (
    MoEConfig,
    TransformerConfig,
    decode_step,
    forward,
    generate,
    init_params,
    prefill,
)


def _cfg(**kw):
    base = dict(
        vocab=97,
        d_model=32,
        n_layers=2,
        n_heads=2,
        d_ff=64,
        dtype=jnp.float32,
        use_flash=False,
        remat=False,
    )
    base.update(kw)
    return TransformerConfig(**base)


def test_decode_matches_full_forward():
    """Each decode_step's logits equal the full forward's last-position
    logits on the same prefix — the KV cache is exact, not approximate."""
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, cfg.vocab)

    logits, cache = prefill(params, prompt, cfg, max_seq=16)
    full = forward(params, prompt, cfg)
    assert np.allclose(np.asarray(logits), np.asarray(full[:, -1]), atol=1e-3)

    seq = prompt
    for step in range(4):
        nxt = jnp.argmax(logits, axis=-1)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        logits, cache = decode_step(params, cache, nxt, cfg)
        full = forward(params, seq, cfg)
        assert np.allclose(
            np.asarray(logits), np.asarray(full[:, -1]), atol=1e-3
        ), f"divergence at decode step {step}"


def test_generate_greedy_matches_forward_argmax():
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 4), 0, cfg.vocab)
    out = generate(params, prompt, cfg, max_new=5)
    assert out.shape == (1, 5)

    # reference: greedy re-forwarding the growing sequence
    seq = prompt
    expected = []
    for _ in range(5):
        logits = forward(params, seq, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        expected.append(int(nxt[0]))
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    assert [int(t) for t in out[0]] == expected


def test_generate_sampled_is_deterministic_per_key():
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jnp.ones((2, 3), jnp.int32)
    a = generate(params, prompt, cfg, max_new=4, rng=jax.random.PRNGKey(7),
                 temperature=1.0)
    b = generate(params, prompt, cfg, max_new=4, rng=jax.random.PRNGKey(7),
                 temperature=1.0)
    assert jnp.array_equal(a, b)
    assert a.shape == (2, 4)
    # different keys must produce at least one different sequence among a
    # handful of tries (an rng-ignoring bug would make them ALL identical)
    diverged = any(
        not jnp.array_equal(
            a,
            generate(params, prompt, cfg, max_new=4,
                     rng=jax.random.PRNGKey(100 + i), temperature=1.0),
        )
        for i in range(5)
    )
    assert diverged, "sampling ignored the rng"


def test_decode_with_moe_ffn():
    cfg = _cfg(moe=MoEConfig(n_experts=2, experts_per_token=2, capacity_factor=4.0))
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jnp.ones((1, 4), jnp.int32)
    logits, cache = prefill(params, prompt, cfg, max_seq=8)
    full = forward(params, prompt, cfg)
    assert np.allclose(np.asarray(logits), np.asarray(full[:, -1]), atol=1e-3)
    nxt = jnp.argmax(logits, axis=-1)
    logits2, cache = decode_step(params, cache, nxt, cfg)
    assert jnp.all(jnp.isfinite(logits2))


def test_tp_sharded_generate_matches_single_device():
    """VERDICT r4 #5: generate() on a tp=2 mesh (params sharded per
    param_specs, KV cache sharded over tp on kv heads) produces exactly the
    single-device greedy tokens."""
    from jax.sharding import NamedSharding

    from odh_kubeflow_tpu.models import generate, param_specs
    from odh_kubeflow_tpu.parallel import MeshPlan

    cfg = TransformerConfig(
        vocab=128,
        d_model=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=128,
        max_seq=64,
        dtype=jnp.float32,
        use_flash=False,
        remat=False,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab)
    want = generate(params, prompt, cfg, max_new=12)

    mesh = MeshPlan(tp=2).build(jax.devices()[:2])
    specs = param_specs(cfg, mesh)
    sharded = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs
    )
    got = generate(sharded, prompt, cfg, max_new=12, mesh=mesh)
    # cache buffers actually shard: compile once more and inspect
    assert (np.asarray(got) == np.asarray(want)).all()


def test_tp_sharded_generate_sampled_matches():
    """Sampled path under tp: same rng -> same tokens as single-device."""
    from jax.sharding import NamedSharding

    from odh_kubeflow_tpu.models import generate, param_specs
    from odh_kubeflow_tpu.parallel import MeshPlan

    cfg = TransformerConfig(
        vocab=128, d_model=64, n_layers=2, n_heads=4, d_ff=128, max_seq=64,
        dtype=jnp.float32, use_flash=False, remat=False,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab)
    rng = jax.random.PRNGKey(7)
    want = generate(params, prompt, cfg, max_new=8, rng=rng, temperature=0.8)
    mesh = MeshPlan(tp=2).build(jax.devices()[:2])
    specs = param_specs(cfg, mesh)
    sharded = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs
    )
    got = generate(sharded, prompt, cfg, max_new=8, rng=rng, temperature=0.8,
                   mesh=mesh)
    assert (np.asarray(got) == np.asarray(want)).all()
