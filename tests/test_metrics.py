"""Controller metrics (controllers/metrics.py): reference's five series +
the TPU-native gauges (chips bound, per-accelerator capacity)."""
from odh_kubeflow_tpu.api.core import Container, ResourceRequirements
from odh_kubeflow_tpu.controllers import constants as C

def test_metrics_scrape_counts_clamped_sts_and_capacity():
    """The running-notebook scrape matches clamped STS names (long notebook
    names must still count) and reports per-accelerator chip capacity from
    Node allocatable."""
    from odh_kubeflow_tpu.api.apps import StatefulSet
    from odh_kubeflow_tpu.api.core import Node
    from odh_kubeflow_tpu.cluster import Client, Store
    from odh_kubeflow_tpu.controllers.metrics import NotebookMetrics
    from odh_kubeflow_tpu.controllers.notebook import statefulset_name
    from odh_kubeflow_tpu.runtime.metrics import Registry

    store = Store()
    client = Client(store)
    long_name = "wb-" + "y" * 60
    sts = StatefulSet()
    sts.metadata.name = statefulset_name(long_name)
    sts.metadata.namespace = "u"
    sts.metadata.labels = {C.NOTEBOOK_NAME_LABEL: long_name}
    sts.spec.template.metadata.labels = {C.NOTEBOOK_NAME_LABEL: long_name}
    sts.spec.template.spec.containers = [
        Container(name="c", image="i", resources=ResourceRequirements(
            requests={"google.com/tpu": "4"}))
    ]
    client.create(sts)
    created = client.get(StatefulSet, "u", sts.metadata.name)
    created.status.ready_replicas = 1
    client.update_status(created)

    node = Node()
    node.metadata.name = "tpu-node-0"
    node.metadata.labels = {"cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice"}
    node.status.allocatable = {"google.com/tpu": "4"}
    client.create(node)

    registry = Registry()
    metrics = NotebookMetrics(registry, client)
    rendered = registry.render()
    assert "notebook_running_total 1" in rendered
    assert "notebook_tpu_chips_bound 4" in rendered
    assert 'tpu_chips_allocatable{accelerator="tpu-v5-lite-podslice"} 4' in rendered

