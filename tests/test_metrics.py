"""Controller metrics (controllers/metrics.py): reference's five series +
the TPU-native gauges (chips bound, per-accelerator capacity) — plus the
Prometheus text-exposition contract (ISSUE 2 satellites): a round-trip
parser validates HELP/TYPE ordering, counter `_total` naming, cumulative
histogram buckets and the mandatory `le="+Inf"` bucket, and label-value
escaping, against both synthetic registries and the LIVE global registry
after a fault-injection scenario."""
import re

import pytest

from odh_kubeflow_tpu.api.core import Container, ResourceRequirements
from odh_kubeflow_tpu.controllers import constants as C

# ---------------------------------------------------------------------------
# text-exposition parser (the scraper's view, minimal but strict)
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+(\S+)$")
_UNESCAPE = {"\\": "\\", '"': '"', "n": "\n"}


def _parse_labels(raw: str) -> dict:
    labels = {}
    i = 0
    while i < len(raw):
        m = re.match(r'([a-zA-Z_][a-zA-Z0-9_]*)="', raw[i:])
        assert m, f"bad label segment at {raw[i:]!r}"
        key = m.group(1)
        i += m.end()
        val = []
        while True:
            assert i < len(raw), f"unterminated label value in {raw!r}"
            ch = raw[i]
            if ch == "\\":
                esc = raw[i + 1]
                assert esc in _UNESCAPE, f"bad escape \\{esc} in {raw!r}"
                val.append(_UNESCAPE[esc])
                i += 2
            elif ch == '"':
                i += 1
                break
            else:
                assert ch != "\n"
                val.append(ch)
                i += 1
        labels[key] = "".join(val)
        if i < len(raw) and raw[i] == ",":
            i += 1
    return labels


def parse_exposition(text: str) -> dict:
    """{family: {"help": str, "type": str, "samples": [(name, labels, value)]}}.
    Asserts the structural contract a standard scraper enforces: HELP/TYPE
    precede samples, every sample belongs to a declared family, values parse
    as floats."""
    families: dict = {}
    current = None
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_ = rest.partition(" ")
            families[name] = {"help": help_, "type": None, "samples": []}
            current = name
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, type_ = rest.partition(" ")
            assert name in families, f"TYPE before HELP for {name}"
            families[name]["type"] = type_
            current = name
            continue
        assert not line.startswith("#"), f"unknown comment line {line!r}"
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable sample line {line!r}"
        sample_name, _, raw_labels, raw_value = m.groups()
        family = sample_name
        for suffix in ("_bucket", "_sum", "_count"):
            if family not in families and family.endswith(suffix):
                family = family[: -len(suffix)]
        assert family in families, f"sample {sample_name} has no HELP/TYPE"
        assert current == family, f"sample {sample_name} outside its family block"
        labels = _parse_labels(raw_labels) if raw_labels else {}
        value = float(raw_value)  # raises on junk
        families[family]["samples"].append((sample_name, labels, value))
    return families


def assert_conventions(families: dict) -> None:
    """Naming + histogram-shape conventions (the metrics-lint contract)."""
    for name, fam in families.items():
        assert fam["type"] in ("counter", "gauge", "histogram"), name
        if fam["type"] == "counter":
            assert name.endswith("_total"), f"counter {name} missing _total"
        if fam["type"] == "histogram":
            by_series: dict = {}
            for sample_name, labels, value in fam["samples"]:
                if sample_name == f"{name}_bucket":
                    key = tuple(sorted((k, v) for k, v in labels.items() if k != "le"))
                    by_series.setdefault(key, {})[labels["le"]] = value
            for key, buckets in by_series.items():
                assert "+Inf" in buckets, f"{name}{dict(key)} missing +Inf bucket"
                finite = sorted(
                    (float(le), c) for le, c in buckets.items() if le != "+Inf"
                )
                counts = [c for _, c in finite] + [buckets["+Inf"]]
                assert counts == sorted(counts), f"{name} buckets not cumulative"
                count_samples = [
                    v
                    for sn, labels, v in fam["samples"]
                    if sn == f"{name}_count"
                    and tuple(sorted(labels.items())) == key
                ]
                assert count_samples and count_samples[0] == buckets["+Inf"], (
                    f"{name}_count != +Inf bucket"
                )


# ---------------------------------------------------------------------------
# exposition-format unit tests
# ---------------------------------------------------------------------------


@pytest.mark.observability
def test_histogram_renders_inf_bucket_and_counts_overflow():
    """Observations above the largest finite bucket must still appear — in
    the +Inf bucket (and _count/_sum); the seed dropped them entirely."""
    from odh_kubeflow_tpu.runtime.metrics import Registry

    registry = Registry()
    h = registry.histogram("req_seconds", "request latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(50.0)  # beyond the largest bucket
    families = parse_exposition(registry.render())
    assert_conventions(families)
    buckets = {
        labels["le"]: v
        for name, labels, v in families["req_seconds"]["samples"]
        if name == "req_seconds_bucket"
    }
    assert buckets["0.1"] == 1 and buckets["1.0"] == 1
    assert buckets["+Inf"] == 2  # the overflow observation is visible
    sums = [v for n, _, v in families["req_seconds"]["samples"] if n == "req_seconds_sum"]
    assert sums == [pytest.approx(50.05)]


@pytest.mark.observability
def test_label_values_escaped():
    """Quotes, backslashes and newlines in label values must round-trip
    through the exposition text (the seed emitted them raw)."""
    from odh_kubeflow_tpu.runtime.metrics import Registry

    registry = Registry()
    c = registry.counter("weird_total", "weird labels", labels=("reason",))
    hostile = 'say "hi"\\path\nnewline'
    c.inc(reason=hostile)
    text = registry.render()
    families = parse_exposition(text)
    assert_conventions(families)
    (sample,) = families["weird_total"]["samples"]
    assert sample[1]["reason"] == hostile  # escape -> parse round-trip
    assert "\n".join(text.splitlines()) == text.rstrip("\n")  # no broken lines


@pytest.mark.observability
def test_help_newlines_escaped():
    from odh_kubeflow_tpu.runtime.metrics import Registry

    registry = Registry()
    registry.counter("multi_total", "line one\nline two")
    families = parse_exposition(registry.render())
    assert families["multi_total"]["help"] == "line one\\nline two"


@pytest.mark.observability
def test_gauge_dec_and_histogram_time():
    from odh_kubeflow_tpu.runtime.metrics import Registry

    registry = Registry()
    g = registry.gauge("inflight", "in-flight ops", labels=("queue",))
    g.inc(queue="q")
    g.inc(queue="q")
    g.dec(queue="q")
    assert g.value(queue="q") == 1.0

    h = registry.histogram("op_seconds", "op latency", labels=("queue",), buckets=(0.5, 5))
    with h.time(queue="q"):
        pass
    assert h._totals[("q",)] == 1
    assert h._sums[("q",)] < 0.5  # the no-op block cannot take half a second


@pytest.mark.observability
def test_live_registry_exposition_after_fault_scenario():
    """The GLOBAL registry (everything the manager serves on /metrics) parses
    cleanly and satisfies the conventions after a fault-injection scenario
    has exercised the resilience counters (watch drop -> restart/relist)."""
    from odh_kubeflow_tpu.api.core import Pod
    from odh_kubeflow_tpu.cluster import SimCluster
    from odh_kubeflow_tpu.runtime.metrics import global_registry, watch_restarts_total

    with SimCluster() as cluster:
        cluster.add_cpu_pool("cpu", nodes=1)
        before = watch_restarts_total.value(kind="Pod")
        cluster.store.sever_watches(kind="Pod")
        deadline = __import__("time").monotonic() + 10
        while __import__("time").monotonic() < deadline:
            if watch_restarts_total.value(kind="Pod") > before:
                break
            __import__("time").sleep(0.01)
        assert watch_restarts_total.value(kind="Pod") > before
        cluster.system.wait_idle(timeout=10)
        families = parse_exposition(global_registry.render())
    assert_conventions(families)
    # the controller-runtime-standard series are live
    for family in (
        "workqueue_depth",
        "workqueue_adds_total",
        "workqueue_queue_duration_seconds",
        "controller_reconcile_duration_seconds",
        "controller_reconcile_total",
        "informer_synced",
        "informer_last_sync_timestamp_seconds",
    ):
        assert family in families, family
    assert any(
        labels.get("kind") == "Pod" and v >= 1
        for name, labels, v in families["informer_watch_restarts_total"]["samples"]
    )


def test_metrics_scrape_counts_clamped_sts_and_capacity():
    """The running-notebook scrape matches clamped STS names (long notebook
    names must still count) and reports per-accelerator chip capacity from
    Node allocatable."""
    from odh_kubeflow_tpu.api.apps import StatefulSet
    from odh_kubeflow_tpu.api.core import Node
    from odh_kubeflow_tpu.cluster import Client, Store
    from odh_kubeflow_tpu.controllers.metrics import NotebookMetrics
    from odh_kubeflow_tpu.controllers.notebook import statefulset_name
    from odh_kubeflow_tpu.runtime.metrics import Registry

    store = Store()
    client = Client(store)
    long_name = "wb-" + "y" * 60
    sts = StatefulSet()
    sts.metadata.name = statefulset_name(long_name)
    sts.metadata.namespace = "u"
    sts.metadata.labels = {C.NOTEBOOK_NAME_LABEL: long_name}
    sts.spec.template.metadata.labels = {C.NOTEBOOK_NAME_LABEL: long_name}
    sts.spec.template.spec.containers = [
        Container(name="c", image="i", resources=ResourceRequirements(
            requests={"google.com/tpu": "4"}))
    ]
    client.create(sts)
    created = client.get(StatefulSet, "u", sts.metadata.name)
    created.status.ready_replicas = 1
    client.update_status(created)

    node = Node()
    node.metadata.name = "tpu-node-0"
    node.metadata.labels = {"cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice"}
    node.status.allocatable = {"google.com/tpu": "4"}
    client.create(node)

    registry = Registry()
    metrics = NotebookMetrics(registry, client)
    rendered = registry.render()
    assert "notebook_running_total 1" in rendered
    assert "notebook_tpu_chips_bound 4" in rendered
    assert 'tpu_chips_allocatable{accelerator="tpu-v5-lite-podslice"} 4' in rendered

