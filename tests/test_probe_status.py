"""Device-visibility readiness gate (controllers/probe_status.py).

SURVEY §7 hard part (a) / VERDICT-r1 acceptance: mesh_ready must reflect what
the hosts' TPU runtimes actually report, not kubelet pod conditions — a host
whose libtpu sees 2 of 4 chips keeps the slice NOT mesh-ready even while all
pods are Ready.
"""
import time

import pytest

from odh_kubeflow_tpu.api.notebook import Notebook, TPUSpec
from odh_kubeflow_tpu.api.core import Container
from odh_kubeflow_tpu.apimachinery import NotFoundError
from odh_kubeflow_tpu.cluster import SimCluster
from odh_kubeflow_tpu.controllers import Config, constants as C
from odh_kubeflow_tpu.main import build_manager
from odh_kubeflow_tpu.probe import sim_agent_behavior

NS = "probe-user"


@pytest.fixture()
def env():
    cluster = SimCluster().start()
    cluster.add_cpu_pool("cpu", nodes=1)
    cluster.add_tpu_pool("v5e", "v5e", "2x2", slices=2)
    cluster.add_tpu_pool("v5p", "v5p", "2x2x4", slices=1)
    agents = {}
    # dim-0 / big-2 are born with degraded visibility (setting it after the
    # pod starts would race the probe controller's first poll). The dict is
    # captured by reference inside the behavior, so tests that need a pod to
    # be REBORN degraded (host-loss downgrade) mutate visible_chips before
    # deleting the pod.
    visible_chips = {"dim-0": 2, "big-2": 3}
    cluster.add_pod_behavior(sim_agent_behavior(agents, visible_chips=visible_chips))
    config = Config(readiness_probe_period_s=0.2)
    mgr = build_manager(cluster.store, config, http_get=cluster.http_get)
    mgr.start()
    yield cluster, agents, visible_chips
    mgr.stop()
    cluster.stop()


def mk_nb(name, topology="2x2", accelerator="v5e"):
    nb = Notebook()
    nb.metadata.name = name
    nb.metadata.namespace = NS
    nb.spec.template.spec.containers = [Container(name=name, image="jax:1")]
    nb.spec.tpu = TPUSpec(accelerator=accelerator, topology=topology)
    return nb


def wait_for(fn, timeout=20, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            out = fn()
            if out:
                return out
        except NotFoundError:
            pass
        time.sleep(0.05)
    raise AssertionError(f"timeout: {msg}")


def get_nb(cluster, name):
    return cluster.client.get(Notebook, NS, name)


def test_partial_chip_visibility_blocks_mesh_ready(env):
    """Pods Ready but one host reports 2/4 chips -> mesh_ready stays false
    and chips_visible reports the true count; full visibility flips it."""
    cluster, agents, _ = env
    cluster.client.create(mk_nb("dim"))  # dim-0 reports 2/4 from birth
    wait_for(
        lambda: get_nb(cluster, "dim").status.ready_replicas == 1,
        msg="pod ready",
    )
    # give the probe loop several cycles: the gate must hold at 2 chips
    wait_for(
        lambda: (get_nb(cluster, "dim").status.tpu or None)
        and get_nb(cluster, "dim").status.tpu.chips_visible == 2,
        msg="probe saw 2 chips",
    )
    nb = get_nb(cluster, "dim")
    assert nb.status.ready_replicas == 1  # pods ARE ready...
    assert nb.status.tpu.mesh_ready is False  # ...but the slice is NOT
    assert nb.status.tpu.first_ready_time == ""

    # chips appear -> gate opens, first_ready_time anchors the latency metric
    agents["dim-0"].monitor.chips = 4
    nb = wait_for(
        lambda: (
            lambda n: n if n.status.tpu and n.status.tpu.mesh_ready else None
        )(get_nb(cluster, "dim")),
        msg="mesh ready",
    )
    assert nb.status.tpu.chips_visible == 4
    assert nb.status.tpu.first_ready_time != ""


def test_multihost_gate_requires_every_host(env):
    """v5p 2x2x4 = 4 hosts: one degraded host (3/4 chips) holds the whole
    slice; chips_visible aggregates per-host reports (15, not 16)."""
    cluster, agents, _ = env
    cluster.client.create(mk_nb("big", topology="2x2x4", accelerator="v5p"))
    wait_for(
        lambda: get_nb(cluster, "big").status.ready_replicas == 4,
        msg="all pods ready",
        timeout=45,
    )
    wait_for(
        lambda: (get_nb(cluster, "big").status.tpu or None)
        and get_nb(cluster, "big").status.tpu.chips_visible == 15,
        msg="aggregated 15 chips",
    )
    assert get_nb(cluster, "big").status.tpu.mesh_ready is False

    agents["big-2"].monitor.chips = 4
    nb = wait_for(
        lambda: (
            lambda n: n if n.status.tpu and n.status.tpu.mesh_ready else None
        )(get_nb(cluster, "big")),
        msg="mesh ready",
    )
    assert nb.status.tpu.chips_visible == 16


def test_chip_loss_after_ready_revokes_gate_but_keeps_first_ready(env):
    """The heartbeat re-detects chip loss; first_ready_time is immutable."""
    cluster, agents, _ = env
    cluster.client.create(mk_nb("flaky"))
    nb = wait_for(
        lambda: (
            lambda n: n if n.status.tpu and n.status.tpu.mesh_ready else None
        )(get_nb(cluster, "flaky")),
        msg="initially ready",
    )
    first = nb.status.tpu.first_ready_time
    assert first

    agents["flaky-0"].monitor.chips = 1
    nb = wait_for(
        lambda: (
            lambda n: n if n.status.tpu and not n.status.tpu.mesh_ready else None
        )(get_nb(cluster, "flaky")),
        msg="gate revoked",
    )
    assert nb.status.tpu.chips_visible == 1
    assert nb.status.tpu.first_ready_time == first


def test_unreachable_probe_keeps_gate_closed(env):
    """No reachable agent (probe-less image): ready pods alone do not open
    the gate — device truth is required."""
    cluster, agents, _ = env
    nb = mk_nb("mute")
    cluster.client.create(nb)
    wait_for(lambda: "mute-0" in agents, msg="agent")
    # sever the probe: agent reports errors by closing its server
    agents["mute-0"].close()
    wait_for(
        lambda: get_nb(cluster, "mute").status.ready_replicas == 1,
        msg="pod ready",
    )
    # condition-wait, not a fixed sleep: if the probe controller sampled the
    # agent in the instant before close(), mesh_ready may flash True — the
    # contract is that an unreachable probe CLOSES the gate within a probe
    # cycle, i.e. the gate is eventually (and then stably) closed
    wait_for(
        lambda: (
            lambda t: t is None or t.mesh_ready is False
        )(get_nb(cluster, "mute").status.tpu),
        timeout=20, msg="gate closed with probe unreachable",
    )
    time.sleep(0.5)  # several probe periods: stays closed
    tpu = get_nb(cluster, "mute").status.tpu
    assert tpu is None or tpu.mesh_ready is False


def test_mesh_ready_downgrades_after_host_loss(env):
    """Bring-up probing is gated on pod readiness, but a DEGRADED slice must
    still downgrade: once mesh_ready is published, losing a host flips it
    back off (and the chip count drops) even though ready_pods < hosts."""
    from odh_kubeflow_tpu.api.core import Pod

    cluster, agents, visible_chips = env
    cluster.client.create(mk_nb("lossy", topology="2x2x4", accelerator="v5p"))
    got = wait_for(
        lambda: (
            lambda n: n if n.status.tpu and n.status.tpu.mesh_ready else None
        )(get_nb(cluster, "lossy")),
        msg="mesh ready", timeout=60,
    )
    assert got.status.tpu.chips_visible == 16

    # lose a host: the probe cycle must observe the gap and downgrade.
    # The STS-analog recreates the pod (level-triggered), and a reborn
    # fully-sighted agent would flip mesh_ready back on — under CPU
    # contention the 50 ms poll below can miss that transient False window
    # entirely (the round-4 flake). Degrade the REBORN host's visibility
    # first so the downgraded state is stable until observed.
    visible_chips["lossy-2"] = 0
    cluster.client.delete(Pod, NS, "lossy-2")
    wait_for(
        lambda: (
            lambda n: True
            if n.status.tpu and not n.status.tpu.mesh_ready else None
        )(get_nb(cluster, "lossy")),
        msg="mesh downgraded", timeout=60,
    )
