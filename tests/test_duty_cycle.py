"""Measured duty cycle (VERDICT-r1 next #5): the culling signal must be a
measurement, not an honor system.

Acceptance: a plain-`jax.numpy` busy loop — never importing
odh_kubeflow_tpu.parallel, never calling record_activity — keeps its
notebook alive under an aggressive culler, because the agent's
JaxTPUMonitor detects device activity by sampling runtime state; and the
libtpu runtime-metrics endpoint (TPU_RUNTIME_METRICS_PORTS) is actually
scraped when present. Reference role anchor: culling_controller.go:243-313.
"""
import threading
import time

import pytest

from odh_kubeflow_tpu.probe.agent import (
    JaxTPUMonitor,
    KernelState,
    NotebookAgent,
    parse_duty_cycle_metrics,
)


def test_parse_duty_cycle_metrics_variants():
    text = """
# HELP tpu_runtime_duty_cycle_pct Duty cycle percent.
# TYPE tpu_runtime_duty_cycle_pct gauge
tpu_runtime_duty_cycle_pct{chip="0"} 62.5
tpu_runtime_duty_cycle_pct{chip="1"} 41.0
memory_bandwidth_util 0.9
"""
    assert parse_duty_cycle_metrics(text) == pytest.approx(0.625)
    assert parse_duty_cycle_metrics("tensorcore_duty_cycle 0.25\n") == pytest.approx(0.25)
    assert parse_duty_cycle_metrics("unrelated_metric 5\n") is None
    assert parse_duty_cycle_metrics("") is None


def test_scrape_libtpu_metrics_port():
    """The injected TPU_RUNTIME_METRICS_PORTS endpoint is consumed: duty
    cycle reflects the runtime's own gauge with zero workload cooperation."""
    from http.server import BaseHTTPRequestHandler, HTTPServer

    payload = b"# TYPE x gauge\ntpu_device_duty_cycle_percent 87.0\n"

    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            self.send_response(200)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        mon = JaxTPUMonitor(metrics_port=srv.server_address[1])
        assert mon.scrape_runtime_duty_cycle() == pytest.approx(0.87)
        assert mon.duty_cycle() == pytest.approx(0.87)
    finally:
        srv.shutdown()
        srv.server_close()


def test_sampler_detects_plain_jax_activity():
    """Runtime-state sampling: allocating/discarding arrays via plain
    jax.numpy flips the fingerprint -> activity recorded."""
    import jax.numpy as jnp

    mon = JaxTPUMonitor(metrics_port=0, window_s=10.0, sample_period_s=0.05)
    mon.sample_once()  # baseline fingerprint
    keep = [jnp.ones((8, 8)) * i for i in range(3)]  # new live arrays
    assert mon.sample_once() is True
    assert mon.duty_cycle() > 0.0
    assert mon.last_busy() > 0.0
    # steady state (no new device work): fingerprint stable
    assert mon.sample_once() is False
    del keep


def test_plain_jax_busy_loop_survives_aggressive_culler():
    """THE acceptance test: Jupyter kernels idle for an hour, culler firing
    every 100ms with a 1s idle threshold — but a background thread doing
    plain jax.numpy work keeps the TPU signal busy, so the notebook lives.
    Temporal control (the agent samples this process's runtime, so a
    parallel idle notebook would see the same activity): once the device
    work stops, the same notebook IS culled."""
    import jax.numpy as jnp

    from odh_kubeflow_tpu.api.core import Container
    from odh_kubeflow_tpu.api.notebook import Notebook, TPUSpec
    from odh_kubeflow_tpu.cluster import SimCluster
    from odh_kubeflow_tpu.cluster.kubelet import PodDecision
    from odh_kubeflow_tpu.controllers import Config, constants as C
    from odh_kubeflow_tpu.main import build_manager

    cluster = SimCluster().start()
    cluster.add_tpu_pool("v5e", "v5e", "2x2", slices=2)

    agents = {}

    def real_monitor_behavior(pod):
        if not pod.metadata.labels.get(C.NOTEBOOK_NAME_LABEL):
            return None
        key = pod.metadata.name
        if key not in agents:
            kernels = KernelState()
            kernels.set_idle(time.time() - 3600)  # Jupyter says: idle for 1h
            monitor = JaxTPUMonitor(
                chips_expected=4, metrics_port=0, window_s=5.0, sample_period_s=0.05
            )
            agents[key] = NotebookAgent(monitor=monitor, kernels=kernels)
        return PodDecision(serve=lambda p: agents[key].serve())

    cluster.add_pod_behavior(real_monitor_behavior)

    config = Config(
        enable_culling=True,
        cull_idle_time_min=1.0 / 60.0,  # 1s idle threshold
        idleness_check_period_min=0.1 / 60.0,  # 100ms cadence
        tpu_idle_threshold=0.005,
        readiness_probe_period_s=0.2,
    )
    mgr = build_manager(cluster.store, config, http_get=cluster.http_get)
    mgr.start()

    stop_work = threading.Event()

    def busy_loop():
        # plain JAX — no odh_kubeflow_tpu.parallel, no record_activity
        x = jnp.ones((32, 32))
        while not stop_work.is_set():
            x = (x @ x.T) / 33.0
            x.block_until_ready()
            time.sleep(0.01)

    worker = threading.Thread(target=busy_loop, daemon=True)
    worker.start()
    try:
        nb = Notebook()
        nb.metadata.name = "busy-nb"
        nb.metadata.namespace = "u"
        nb.spec.template.spec.containers = [Container(name="busy-nb", image="jax:1")]
        nb.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2")
        cluster.client.create(nb)

        def annotations():
            return cluster.client.get(Notebook, "u", "busy-nb").metadata.annotations

        # creation lock (webhook-injected) clears once satellites exist
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if C.STOP_ANNOTATION not in annotations():
                break
            time.sleep(0.1)
        assert C.STOP_ANNOTATION not in annotations(), "lock never removed"

        # phase 1: device work running -> survives many cull cycles despite
        # hour-stale Jupyter kernels (GPU-era signal alone would kill it)
        deadline = time.monotonic() + 6
        saw_probe = False
        while time.monotonic() < deadline:
            assert C.STOP_ANNOTATION not in annotations(), "busy notebook culled"
            saw_probe = saw_probe or C.LAST_ACTIVITY_ANNOTATION in annotations()
            time.sleep(0.2)
        assert saw_probe, "culler never probed the notebook"

        # phase 2: stop device work — the same notebook is culled shortly
        # after the sampling window drains, proving phase 1's survival came
        # from measured activity rather than a dead signal
        stop_work.set()
        worker.join(timeout=5)
        deadline = time.monotonic() + 30
        culled = False
        while time.monotonic() < deadline:
            if C.STOP_ANNOTATION in annotations():
                culled = True
                break
            time.sleep(0.2)
        assert culled, "notebook with stopped workload was never culled"
    finally:
        stop_work.set()
        mgr.stop()
        cluster.stop()
