"""SLO engine / burn-rate alerting / black-box prober / flight recorder
(ISSUE 5 tentpole).

Unit halves run against a fake clock (deterministic burn-rate math, alert
lifecycle, inhibition, ring/bundle semantics); the acceptance soak at the
bottom runs the seeded slice bad day with the full judgement layer wired:
a burn-rate alert fires within the fast window, is mirrored as an Event +
`DegradedSLO` condition on the affected Notebook, resolves after repair
completes, and the flight recorder produces exactly the expected incident
bundles, retrievable via /debug/incidents. The calm-path overhead test
bounds the whole layer at <10% added per-reconcile cost.
"""
from __future__ import annotations

import json
import logging
import threading
import time
import urllib.error
import urllib.request

import pytest

from odh_kubeflow_tpu.runtime.alerts import AlertManager, AlertRule, default_rules
from odh_kubeflow_tpu.runtime.flightrecorder import FlightRecorder, recorder
from odh_kubeflow_tpu.runtime.metrics import Registry
from odh_kubeflow_tpu.runtime.slo import (
    SLO,
    EventRatioIndicator,
    GaugeIndicator,
    LatencyIndicator,
    SLOEngine,
    default_slos,
)
from odh_kubeflow_tpu.utils import tracing

pytestmark = pytest.mark.slo


# ---------------------------------------------------------------------------
# burn-rate math (fake clock, private registry)
# ---------------------------------------------------------------------------


def _mk_engine(reg, slos, t):
    return SLOEngine(
        registry=reg, slos=slos, clock=lambda: t[0], window_scale=1.0,
        eval_period_s=1.0,
    )


def test_latency_slo_windowed_compliance_and_burn():
    reg = Registry()
    hist = reg.histogram("lat_seconds", "h", buckets=(1, 5, 10))
    slo = SLO("lat", objective=0.9, indicator=LatencyIndicator("lat_seconds", 1.0))
    t = [1000.0]
    eng = _mk_engine(reg, [slo], t)
    eng.evaluate()  # baseline sample before any events

    for _ in range(9):
        hist.observe(0.5)
    hist.observe(7.0)
    t[0] += 10
    status = eng.evaluate()["lat"]
    w = status["windows"]["5m"]
    # 9 good / 10 total against a 10% budget: burning exactly the budget
    assert w["compliance"] == pytest.approx(0.9)
    assert w["burn_rate"] == pytest.approx(1.0)

    # a burst of pure failures: 5m window now sees 9 good / 20 total
    t[0] += 60
    for _ in range(10):
        hist.observe(7.0)
    status = eng.evaluate()["lat"]
    assert status["windows"]["5m"]["compliance"] == pytest.approx(9 / 20)
    assert status["windows"]["5m"]["burn_rate"] == pytest.approx(
        (1 - 9 / 20) / 0.1
    )

    # the outage ages out of the fast window but stays in the slow one
    t[0] += 301
    status = eng.evaluate()["lat"]
    assert status["windows"]["5m"]["compliance"] == 1.0  # no events in window
    assert status["windows"]["5m"]["burn_rate"] == 0.0
    assert status["windows"]["6h"]["compliance"] == pytest.approx(9 / 20)


def test_event_ratio_indicator_good_labels():
    reg = Registry()
    probes = reg.counter("probes_total", "p", labels=("result",))
    slo = SLO(
        "canary", objective=0.9,
        indicator=EventRatioIndicator("probes_total", (("result", "ok"),)),
    )
    t = [0.0]
    eng = _mk_engine(reg, [slo], t)
    eng.evaluate()
    probes.inc(8, result="ok")
    probes.inc(2, result="timeout")
    t[0] += 10
    status = eng.evaluate()["canary"]
    assert status["windows"]["5m"]["compliance"] == pytest.approx(0.8)
    assert status["windows"]["5m"]["burn_rate"] == pytest.approx(2.0)


def test_gauge_indicator_time_weighted_and_unset_gauge_is_compliant():
    reg = Registry()
    avail = reg.gauge("avail_ratio", "a")
    slo = SLO("avail", objective=0.99, indicator=GaugeIndicator("avail_ratio"))
    t = [0.0]
    eng = _mk_engine(reg, [slo], t)
    # gauge never set: no burn (a fleet with nothing to measure is healthy)
    status = eng.evaluate()["avail"]
    assert status["windows"]["5m"]["compliance"] == 1.0

    avail.set(1.0)
    eng.evaluate()  # integration anchor
    t[0] += 10
    eng.evaluate()  # 10s at 1.0
    avail.set(0.5)
    t[0] += 10
    status = eng.evaluate()["avail"]  # +10s at 0.5
    assert status["windows"]["5m"]["compliance"] == pytest.approx(0.75)
    assert status["windows"]["5m"]["burn_rate"] == pytest.approx(0.25 / 0.01)


def test_window_scale_shrinks_real_windows_keeps_names():
    eng = SLOEngine(registry=Registry(), slos=default_slos(), window_scale=0.002)
    assert eng.windows["5m"] == pytest.approx(0.6)
    assert eng.windows["6h"] == pytest.approx(43.2)
    assert set(eng.windows) == {"5m", "30m", "1h", "6h"}


# ---------------------------------------------------------------------------
# alert lifecycle: fire / dedup / resolve / inhibition
# ---------------------------------------------------------------------------


def _status(category="readiness", burn_long=0.0, burn_short=0.0):
    return {
        "s": {
            "category": category,
            "windows": {
                "1h": {"burn_rate": burn_long, "compliance": 1.0},
                "5m": {"burn_rate": burn_short, "compliance": 1.0},
            },
        }
    }


def test_alert_fires_dedups_and_resolves_on_long_window():
    t = [100.0]
    rule = AlertRule("s-fast-burn", "s", "1h", "5m", 14.4)
    am = AlertManager(rules=[rule], clock=lambda: t[0])

    # short window alone must NOT fire (outage already over / just starting)
    am.evaluate(_status(burn_long=1.0, burn_short=99.0))
    assert not am.firing
    am.evaluate(_status(burn_long=99.0, burn_short=1.0))
    assert not am.firing

    am.evaluate(_status(burn_long=20.0, burn_short=20.0))
    assert "s-fast-burn" in am.firing
    fired = [h for h in am.history if h["event"] == "fired"]
    assert len(fired) == 1

    # still breaching: deduplicated, not re-fired
    t[0] += 5
    am.evaluate(_status(burn_long=21.0, burn_short=21.0))
    assert len([h for h in am.history if h["event"] == "fired"]) == 1

    # short window recovers first: still firing (resolve keys off long only)
    am.evaluate(_status(burn_long=20.0, burn_short=0.5))
    assert "s-fast-burn" in am.firing

    t[0] += 5
    am.evaluate(_status(burn_long=2.0, burn_short=0.5))
    assert not am.firing
    resolved = [h for h in am.history if h["event"] == "resolved"]
    assert len(resolved) == 1
    assert resolved[0]["resolved_at"] - resolved[0]["since"] == pytest.approx(10.0)


def test_slice_repair_inhibits_readiness_but_not_availability():
    repair_active = [True]
    rules = [
        AlertRule("ready-fast", "s", "1h", "5m", 14.4),
    ]
    am = AlertManager(rules=rules, clock=lambda: 0.0)
    am.register_inhibitor(
        "readiness", lambda: repair_active[0], name="slice-repair-in-progress"
    )

    am.evaluate(_status(category="readiness", burn_long=50, burn_short=50))
    assert not am.firing, "readiness alert must be inhibited mid-repair"

    # the same breach on an availability-category SLO pages right through
    am.evaluate(_status(category="availability", burn_long=50, burn_short=50))
    assert "ready-fast" in am.firing
    del am.firing["ready-fast"]

    # repair over: the readiness breach now fires
    repair_active[0] = False
    am.evaluate(_status(category="readiness", burn_long=50, burn_short=50))
    assert "ready-fast" in am.firing
    assert am.status()["inhibitors"] == {
        "readiness": ["slice-repair-in-progress"]
    }


# ---------------------------------------------------------------------------
# flight recorder: ring, bundles, dedup, capture hooks
# ---------------------------------------------------------------------------


def test_flightrecorder_ring_bounds_and_incident_dedup():
    t = [0.0]
    rec = FlightRecorder(
        capacity=8, max_incidents=2, dedup_window_s=100.0, clock=lambda: t[0]
    )
    for i in range(20):
        rec.record("sample", i=i)
    assert len(rec) == 8  # bounded ring, oldest dropped
    assert [r["i"] for r in rec.records("sample")] == list(range(12, 20))

    first = rec.snapshot("slice-degraded", subject="ns/a")
    same = rec.snapshot("slice-degraded", subject="ns/a")
    assert first == same, "same (reason, subject) within the window: one bundle"
    other = rec.snapshot("slice-degraded", subject="ns/b")
    assert other != first
    assert {i["subject"] for i in rec.incidents()} == {"ns/a", "ns/b"}

    # capped count: a third distinct incident evicts the oldest
    rec.snapshot("repair-failed", subject="ns/c")
    assert len(rec.incidents()) == 2
    assert rec.get(first) is None
    bundle = rec.get(other)
    assert bundle is not None and bundle["reason"] == "slice-degraded"
    assert bundle["records"], "bundle must carry the ring contents"

    # disabled: zero-cost no-op
    rec.set_enabled(False)
    rec.record("sample", i=99)
    assert rec.snapshot("x") is None
    assert len(rec) == 8


def test_flightrecorder_captures_spans_and_log_records():
    recorder.clear()
    tracing.set_enabled(True)
    tracing.record_span("unit.test.span", notebook="obs/nb-1")
    spans = [
        r for r in recorder.records("span") if r["name"] == "unit.test.span"
    ]
    assert spans and spans[-1]["attributes"]["notebook"] == "obs/nb-1"

    logger = logging.getLogger("slo-test-logger")
    handler = recorder.log_handler(level=logging.WARNING)
    logger.addHandler(handler)
    try:
        logger.warning("the dilithium is %s", "depleted")
    finally:
        logger.removeHandler(handler)
    logs = [r for r in recorder.records("log") if "dilithium" in r["message"]]
    assert logs and logs[-1]["level"] == "WARNING"


# ---------------------------------------------------------------------------
# the black-box canary prober
# ---------------------------------------------------------------------------


def test_canary_probe_full_roundtrip_and_cleanup():
    from odh_kubeflow_tpu.api.notebook import Notebook
    from odh_kubeflow_tpu.apimachinery import NotFoundError
    from odh_kubeflow_tpu.cluster import SimCluster
    from odh_kubeflow_tpu.controllers import Config
    from odh_kubeflow_tpu.main import build_manager
    from odh_kubeflow_tpu.runtime.prober import CanaryProber, canary_probes_total

    cluster = SimCluster().start()
    cluster.add_cpu_pool("cpu", nodes=1)
    mgr = build_manager(
        cluster.store, Config(slo_enabled=False), http_get=cluster.http_get
    )
    mgr.start()
    prober = CanaryProber(mgr, period_s=60.0, timeout_s=20.0)
    ok0 = canary_probes_total.value(result="ok")
    try:
        result, latency = prober.probe_once()

        # the canary CR goes away (finalizer cleanup is async): a leaked
        # canary would distort the very availability it measures
        def canary_gone():
            try:
                cluster.client.get(Notebook, prober.namespace, "canary-1")
                return False
            except NotFoundError:
                return True

        _wait_for(canary_gone, msg="canary CR cleaned up")
    finally:
        mgr.stop()
        cluster.stop()
    assert result == "ok" and latency > 0
    assert canary_probes_total.value(result="ok") == ok0 + 1


# ---------------------------------------------------------------------------
# definition lint (the ci/slo_lint.sh contract)
# ---------------------------------------------------------------------------


def test_slo_lint_default_definitions_clean():
    import odh_kubeflow_tpu.runtime.prober  # noqa: F401  (canary families)
    from odh_kubeflow_tpu.analysis.metric_rules import check_slo_definitions
    from odh_kubeflow_tpu.controllers.metrics import NotebookMetrics
    from odh_kubeflow_tpu.runtime.metrics import global_registry

    NotebookMetrics(global_registry)
    slos = default_slos()
    assert check_slo_definitions(slos, default_rules(slos), global_registry) == []


def test_slo_lint_flags_bad_definitions():
    from odh_kubeflow_tpu.analysis.metric_rules import check_slo_definitions

    reg = Registry()
    reg.histogram("real_seconds", "h", buckets=(1, 5))
    bad_slos = [
        SLO("ghost", 0.9, LatencyIndicator("no_such_metric_seconds", 1.0)),
        SLO("offgrid", 0.9, LatencyIndicator("real_seconds", 2.5)),  # not a bucket
        SLO("outside", 1.5, GaugeIndicator("nope_ratio")),
    ]
    bad_rules = [
        AlertRule("dangling", "no-such-slo", "1h", "5m", 14.4),
        AlertRule("badwin", "ghost", "2h", "5m", 14.4),
        # objective 0.9 caps burn at 10x: a 14.4x threshold can never fire
        AlertRule("deadrule", "ghost", "1h", "5m", 14.4),
    ]
    violations = check_slo_definitions(bad_slos, bad_rules, reg)
    text = "\n".join(violations)
    assert "unregistered metric 'no_such_metric_seconds'" in text
    assert "not a bucket boundary" in text
    assert "objective 1.5 outside" in text
    assert "undefined SLO 'no-such-slo'" in text
    assert "unknown window '2h'" in text
    assert "deadrule" in text and "can never fire" in text


def test_default_rules_are_always_feasible():
    """Burn is capped at 1/error_budget: the shipped rules must clamp their
    thresholds under the cap, or low-objective SLOs (p50 at 0.50) ship
    permanently-dead pages."""
    slos = {s.name: s for s in default_slos()}
    for rule in default_rules():
        cap = 1.0 / slos[rule.slo].error_budget
        assert rule.burn_threshold <= cap, (
            f"{rule.name}: threshold {rule.burn_threshold} above max burn {cap}"
        )
    # the high-objective SLOs keep the canonical Google-SRE thresholds
    by_name = {r.name: r for r in default_rules()}
    assert by_name["notebook-availability-fast-burn"].burn_threshold == 14.4
    assert by_name["readiness-latency-p50-fast-burn"].burn_threshold < 2.0


# ---------------------------------------------------------------------------
# acceptance: seeded slice bad day through the full judgement layer
# ---------------------------------------------------------------------------

NS = "repair"


def _wait_for(fn, timeout=30, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(0.05)
    raise AssertionError(f"timeout: {msg}")


def test_bad_day_fires_alert_mirrors_condition_and_bundles_incidents():
    """THE acceptance path: seeded slice bad day -> availability burn-rate
    alert fires within the fast window -> Event + DegradedSLO condition on
    the affected Notebook -> resolves after repair -> exactly the expected
    incident bundles on /debug/incidents."""
    from odh_kubeflow_tpu.api.core import Container, Event, Pod
    from odh_kubeflow_tpu.api.notebook import Notebook, TPUSpec
    from odh_kubeflow_tpu.cluster import SimCluster, seeded_slice_bad_day
    from odh_kubeflow_tpu.controllers import (
        Config,
        NotebookReconciler,
        ProbeStatusController,
        SliceRepairController,
        constants as C,
    )
    from odh_kubeflow_tpu.probe import sim_agent_behavior
    from odh_kubeflow_tpu.runtime import Manager

    fast = Config(
        readiness_probe_period_s=0.15,
        checkpoint_window_s=1.0,
        repair_max_attempts=4,
        repair_backoff_s=0.3,
        repair_backoff_max_s=1.0,
    )
    cluster = SimCluster().start()
    cluster.add_tpu_pool("v5p", "v5p", "2x2x2", slices=2)
    cluster.add_tpu_pool("v5e", "v5e", "2x2", slices=3)
    mgr = Manager(cluster.store)
    NotebookReconciler(mgr, fast).setup()
    ProbeStatusController(mgr, fast, http_get=cluster.http_get).setup()
    repair = SliceRepairController(mgr, fast, http_get=cluster.http_get)
    repair.unreachable_dwell_s = 0.6
    repair.setup()
    agents: dict = {}
    cluster.add_pod_behavior(sim_agent_behavior(agents, duty=0.9, kernels_busy=True))

    # the judgement layer, on scaled windows: 5m -> 0.6s, 1h -> 7.2s
    slo = SLO(
        "notebook-availability",
        objective=0.999,
        indicator=GaugeIndicator("notebook_available_ratio"),
        category="availability",
    )
    engine = SLOEngine(
        registry=mgr.metrics, slos=[slo], window_scale=0.002, eval_period_s=0.05
    )
    rule = AlertRule(
        "availability-fast-burn", "notebook-availability", "1h", "5m", 14.4
    )
    alert_mgr = AlertManager(rules=[rule], manager=mgr, recorder=recorder)
    engine.add_listener(alert_mgr.evaluate)
    mgr.slo_engine = engine
    mgr.alert_manager = alert_mgr
    mgr.flight_recorder = recorder
    mgr.add_service(engine)
    mgr.start()
    endpoints = mgr.serve_endpoints(metrics_port=0, health_port=0, host="127.0.0.1")

    def mk_nb(name, acc, topo):
        nb = Notebook()
        nb.metadata.name = name
        nb.metadata.namespace = NS
        nb.spec.template.spec.containers = [Container(name=name, image="jax:1")]
        nb.spec.tpu = TPUSpec(accelerator=acc, topology=topo)
        return nb

    def get_nb(name):
        return cluster.client.get(Notebook, NS, name)

    def mesh_ready(name):
        nb = get_nb(name)
        return nb.status.tpu is not None and nb.status.tpu.mesh_ready

    def condition(nb, ctype):
        return next((c for c in nb.status.conditions if c.type == ctype), None)

    try:
        names = [("a-pod-0", "v5p", "2x2x2"), ("a-pod-1", "v5p", "2x2x2"),
                 ("a-nb-0", "v5e", "2x2"), ("a-nb-1", "v5e", "2x2")]
        for name, acc, topo in names:
            cluster.client.create(mk_nb(name, acc, topo))
        for name, _, _ in names:
            _wait_for(lambda n=name: mesh_ready(n), msg=f"{name} up")

        # calm baseline: availability gauge settled at 1.0, nothing firing,
        # then wipe the recorder so "exactly the expected bundles" is judged
        # over the bad day alone
        _wait_for(
            lambda: engine.evaluate()["notebook-availability"]["windows"]["1h"][
                "burn_rate"
            ] < rule.burn_threshold and not alert_mgr.firing,
            msg="calm baseline before fault injection",
        )
        recorder.clear()
        alert_mgr.history.clear()

        pod_nodes = {}
        for p in cluster.client.list(Pod, namespace=NS):
            if p.spec.node_name and p.metadata.labels.get(C.NOTEBOOK_NAME_LABEL):
                pod_nodes[p.metadata.name] = p.spec.node_name
        fault_t0 = time.monotonic()
        plan = seeded_slice_bad_day(
            cluster, seed=0x51CE, pod_nodes=pod_nodes, agents=agents, grace_s=0.4
        )
        assert plan["preempted"], "the seeded schedule must preempt something"

        # (1) the burn-rate alert fires within the fast pair's long window
        _wait_for(
            lambda: any(h["event"] == "fired" for h in alert_mgr.history),
            timeout=20, msg="availability burn-rate alert fired",
        )
        fired = next(h for h in alert_mgr.history if h["event"] == "fired")
        assert fired["rule"] == "availability-fast-burn"
        assert time.monotonic() - fault_t0 < engine.windows["1h"] + 5.0, \
            "alert did not fire within the fast window"
        assert fired["notebooks"], "alert must name affected notebooks"

        # (2) mirrored onto the affected Notebook: Event + DegradedSLO=True
        mirrored_ns, _, mirrored_name = fired["notebooks"][0].partition("/")
        _wait_for(
            lambda: any(
                e.reason == "SLOBurnRate"
                and e.involved_object.name == mirrored_name
                for e in cluster.client.list(Event, namespace=mirrored_ns)
            ),
            msg="SLOBurnRate event on the affected notebook",
        )
        # the condition mirror must have LANDED — either still True (alert
        # firing) or already flipped False/Recovered: on a loaded box (the
        # suite now runs two more controllers' watch fan-out) the repair can
        # complete and the fast pair resolve before this wait even starts,
        # and Recovered is itself proof the True mirror happened (only the
        # resolution path writes that reason). Step (3) below still asserts
        # the full True -> False/Recovered lifecycle ends Recovered.
        _wait_for(
            lambda: (c := condition(get_nb(mirrored_name), C.SLO_DEGRADED_CONDITION))
            is not None and (
                c.status == "True"
                or (c.status == "False" and c.reason == "Recovered")
            ),
            msg="DegradedSLO mirrored while firing (or already recovered)",
        )

        # repairs land: maintenance ends, capacity returns
        time.sleep(1.5)
        for node in plan["preempted"]:
            cluster.restore_node(node)

        def settled(name):
            nb = get_nb(name)
            state = nb.metadata.annotations.get(C.TPU_REPAIR_STATE_ANNOTATION, "")
            if state == "failed":
                return any(
                    e.reason == "RepairFailed" and e.involved_object.name == name
                    for e in cluster.client.list(Event, namespace=NS)
                )
            if state:
                return False
            c = condition(nb, C.TPU_DEGRADED_CONDITION)
            return mesh_ready(name) and (c is None or c.status == "False")

        for name, _, _ in names:
            _wait_for(lambda n=name: settled(n), timeout=60,
                      msg=f"{name} neither repaired nor RepairFailed")

        # (3) the alert resolves once the outage ages out of the long window
        _wait_for(
            lambda: not alert_mgr.firing, timeout=40,
            msg="alert resolved after repair",
        )
        resolved = [h for h in alert_mgr.history if h["event"] == "resolved"]
        assert resolved and resolved[-1]["resolved_at"] > resolved[-1]["since"]
        _wait_for(
            lambda: (c := condition(get_nb(mirrored_name), C.SLO_DEGRADED_CONDITION))
            is not None and c.status == "False" and c.reason == "Recovered",
            msg="DegradedSLO cleared with reason Recovered",
        )

        # (4) exactly the expected incident bundles, via /debug/incidents
        degraded = {
            e.involved_object.name
            for e in cluster.client.list(Event, namespace=NS)
            if e.reason == "SliceDegraded"
        }
        failed = {
            e.involved_object.name
            for e in cluster.client.list(Event, namespace=NS)
            if e.reason == "RepairFailed"
        }
        assert degraded, "the bad day must degrade at least one notebook"
        expected = {("slice-degraded", f"{NS}/{n}") for n in degraded}
        expected |= {("repair-failed", f"{NS}/{n}") for n in failed}
        expected |= {
            (f"alert:{h['rule']}", h["slo"])
            for h in alert_mgr.history
            if h["event"] == "fired"
        }
        host, port = endpoints.metrics_address
        with urllib.request.urlopen(
            f"http://{host}:{port}/debug/incidents", timeout=5
        ) as resp:
            listing = json.loads(resp.read())
        observed = {(i["reason"], i["subject"]) for i in listing["incidents"]}
        assert observed == expected

        # every bundle is fetchable and self-contained (ring + CR state)
        some_id = next(
            i["id"] for i in listing["incidents"]
            if i["reason"] == "slice-degraded"
        )
        with urllib.request.urlopen(
            f"http://{host}:{port}/debug/incidents?id={some_id}", timeout=5
        ) as resp:
            bundle = json.loads(resp.read())
        assert bundle["records"], "bundle carries the flight-recorder ring"
        assert bundle["state"], "bundle carries CR/pod state"
        nb_state = next(iter(bundle["state"].values()))
        assert "notebook" in nb_state and "pods" in nb_state

        # (5) /debug/slo and the /debug/ index serve the judgement layer
        with urllib.request.urlopen(
            f"http://{host}:{port}/debug/slo", timeout=5
        ) as resp:
            slo_payload = json.loads(resp.read())
        assert "notebook-availability" in slo_payload["engine"]["slos"]
        assert slo_payload["alerts"]["rules"][0]["name"] == "availability-fast-burn"
        with urllib.request.urlopen(
            f"http://{host}:{port}/debug/", timeout=5
        ) as resp:
            index = resp.read().decode()
        assert "/debug/slo" in index and "/debug/incidents" in index

        assert mgr.healthz(), "a controller/engine thread died during the bad day"
    finally:
        endpoints.stop()
        mgr.stop()
        cluster.stop()
        cluster.faults.clear()


# ---------------------------------------------------------------------------
# /debug/traces filters (ISSUE 5 satellite)
# ---------------------------------------------------------------------------


def test_debug_traces_limit_and_notebook_filters():
    from odh_kubeflow_tpu.cluster import SimCluster
    from odh_kubeflow_tpu.runtime import Manager

    tracing.set_enabled(True)
    # the shape controller spans actually emit: bare notebook name with the
    # namespace as its own attribute
    for i in range(6):
        tracing.record_span(
            "filter.span", notebook=f"nb-{i % 2}", namespace="obs"
        )
    cluster = SimCluster().start()
    mgr = Manager(cluster.store)
    mgr.start()
    endpoints = mgr.serve_endpoints(metrics_port=0, health_port=0, host="127.0.0.1")
    try:
        host, port = endpoints.metrics_address

        def fetch(qs):
            with urllib.request.urlopen(
                f"http://{host}:{port}/debug/traces?{qs}", timeout=5
            ) as resp:
                return json.loads(resp.read())["spans"]

        assert len(fetch("limit=3")) == 3
        # both the documented "ns/name" form and the bare name match the
        # controller-emitted span shape
        only_zero = fetch("notebook=obs/nb-0&name=filter.span")
        assert only_zero and all(
            s["attributes"]["notebook"] == "nb-0" for s in only_zero
        )
        assert fetch("notebook=nb-1&name=filter.span")
        assert fetch("notebook=obs/nb-9&name=filter.span") == []
        mixed = fetch("name=filter.span&limit=2")
        assert len(mixed) == 2
        # malformed limit is a 400, not a stack trace
        try:
            urllib.request.urlopen(
                f"http://{host}:{port}/debug/traces?limit=bogus", timeout=5
            )
            raise AssertionError("limit=bogus must 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        endpoints.stop()
        mgr.stop()
        cluster.stop()


# ---------------------------------------------------------------------------
# calm-path overhead: SLO engine + flight recorder < 10% per reconcile
# ---------------------------------------------------------------------------


def _reconcile_loop_wall(n: int) -> float:
    from odh_kubeflow_tpu.runtime.controller import Controller

    count = [0]
    done = threading.Event()

    def reconciler(req):
        count[0] += 1
        if count[0] >= n:
            done.set()
        return None

    ctrl = Controller("slo-overhead", reconciler)
    ctrl.start()
    try:
        t0 = time.perf_counter()
        for i in range(n):
            ctrl.enqueue("obs", f"nb-{i}")
        assert done.wait(60)
        return time.perf_counter() - t0
    finally:
        ctrl.stop()


def test_slo_and_flightrecorder_overhead_under_ten_percent():
    """Acceptance bound: with the SLO engine ticking and the flight recorder
    sampling every reconcile, the calm path costs <10% extra per reconcile
    (with a 0.5 ms noise floor — the same min-of-runs methodology as the
    PR 2 tracing-overhead test)."""
    n = 300
    _reconcile_loop_wall(50)  # warm imports/threads before measuring

    recorder.set_enabled(False)
    try:
        t_off = min(_reconcile_loop_wall(n) for _ in range(2))
    finally:
        recorder.set_enabled(True)

    engine = SLOEngine(slos=default_slos(), window_scale=0.01, eval_period_s=0.05)
    engine.start()
    try:
        t_on = min(_reconcile_loop_wall(n) for _ in range(2))
    finally:
        engine.stop()

    baseline_per = t_off / n
    added_per = max(0.0, t_on - t_off) / n
    assert added_per < max(0.10 * baseline_per, 0.0005), (
        f"SLO engine + flight recorder add {added_per * 1e3:.3f} ms per "
        f"reconcile ({added_per / baseline_per:.0%} of the "
        f"{baseline_per * 1e3:.3f} ms baseline)"
    )
