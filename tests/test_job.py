"""Gang-scheduled batch/RL TPUJobs (ISSUE 10): all-or-nothing gang
admission (anakin single-gang + sebulba dual-gang atomicity), warm-claim
fast starts off a suspended notebook's slice, checkpoint-preempt-requeue
under the three-class reclaim ordering, host-preemption survival, the
budget queue, and the seeded mixed bad-day soak asserting no job is ever
silently stuck in Admitted/Preempted.

Deterministic tier-1 tests (marker: job); ci/faults.sh reruns the fault
lane under REPEAT + RACECHECK=1 + INVCHECK=1.
"""
import json
import time
from dataclasses import replace

import pytest

from odh_kubeflow_tpu.api.core import Container, Event, Node, Pod
from odh_kubeflow_tpu.api.apps import StatefulSet
from odh_kubeflow_tpu.api.job import LAYOUT_SEBULBA, TPUJob
from odh_kubeflow_tpu.api.notebook import Notebook, TPUSpec
from odh_kubeflow_tpu.cluster import SimCluster, SlicePool
from odh_kubeflow_tpu.cluster.faults import seeded_bad_day
from odh_kubeflow_tpu.cluster.scheduler import (
    Scheduler,
    claim_owner_labels,
    pod_claim_owner,
)
from odh_kubeflow_tpu.controllers import (
    Config,
    NotebookReconciler,
    ProbeStatusController,
    SuspendResumeController,
    TPUJobReconciler,
    constants as C,
)
from odh_kubeflow_tpu.controllers.job import job_gangs, job_priority
from odh_kubeflow_tpu.probe import sim_agent_behavior
from odh_kubeflow_tpu.runtime import Manager
from odh_kubeflow_tpu.runtime import jobmetrics as JM
from odh_kubeflow_tpu.runtime.flightrecorder import recorder
from odh_kubeflow_tpu.tpu import GKE_NODEPOOL_LABEL, plan_slice

pytestmark = pytest.mark.job

NS = "batch"
STEP_PER_CKPT = 30

FAST = Config(
    enable_culling=False,
    suspend_enabled=True,
    readiness_probe_period_s=0.15,
    suspend_checkpoint_window_s=1.0,
    resume_timeout_s=20.0,
    reclaim_pending_grace_s=0.3,
    job_checkpoint_window_s=2.0,
    job_requeue_backoff_s=0.1,
)


def build_env(config=FAST, slices=2):
    """Full three-actor stack (notebook + suspend/reclaim + job controllers)
    over one sim cluster. The workload's step counter lives at the
    transport: every learner-gang /tpu/checkpoint ack advances it by
    STEP_PER_CKPT and is remembered, so tests can assert a resumed job
    restarts from a step its workload actually acked."""
    cluster = SimCluster().start()
    cluster.add_tpu_pool("v5e", "v5e", "2x2", slices=slices)
    steps = {}
    acked = {}

    def http_get(url, timeout=10.0):
        if "/tpu/checkpoint" in url and "-learner-" in url:
            name = url.split("//", 1)[1].split("-learner-", 1)[0]
            steps[name] = steps.get(name, 0) + STEP_PER_CKPT
            acked.setdefault(name, []).append(steps[name])
            return 200, json.dumps(
                {"saved": True, "step": steps[name]}
            ).encode()
        if "/tpu/checkpoint" in url:
            # a churn notebook's suspend checkpoint: instant ack
            return 200, json.dumps({"saved": True, "step": 1}).encode()
        return cluster.http_get(url, timeout=timeout)

    mgr = Manager(cluster.store)
    NotebookReconciler(mgr, config).setup()
    ProbeStatusController(mgr, config, http_get=http_get).setup()
    SuspendResumeController(mgr, config, http_get=http_get).setup()
    TPUJobReconciler(mgr, config, http_get=http_get).setup()
    agents = {}
    cluster.add_pod_behavior(sim_agent_behavior(agents, duty=0.9))
    mgr.start()
    return cluster, mgr, acked


@pytest.fixture()
def env():
    cluster, mgr, acked = build_env()
    yield cluster, mgr, acked
    mgr.stop()
    cluster.stop()
    cluster.faults.clear()


def mk_job(name, steps=90, period=0.2, priority=0, layout=None, actors=None,
           backoff_limit=3, max_runtime_s=0.0):
    job = TPUJob()
    job.metadata.name = name
    job.metadata.namespace = NS
    job.spec.template.spec.containers = [Container(name=name, image="jax:1")]
    job.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2",
                           priority=priority)
    job.spec.steps = steps
    job.spec.checkpoint_period_s = period
    job.spec.backoff_limit = backoff_limit
    job.spec.max_runtime_s = max_runtime_s
    if layout:
        job.spec.layout = layout
    if actors:
        job.spec.actors = actors
    return job


def mk_nb(name, priority=0):
    nb = Notebook()
    nb.metadata.name = name
    nb.metadata.namespace = NS
    nb.spec.template.spec.containers = [Container(name=name, image="jax:1")]
    nb.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2",
                          priority=priority)
    return nb


def wait_for(fn, timeout=30, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(0.05)
    raise AssertionError(f"timeout: {msg}")


def get_job(cluster, name):
    return cluster.client.get(TPUJob, NS, name)


def job_state(cluster, name):
    return get_job(cluster, name).metadata.annotations.get(
        C.JOB_STATE_ANNOTATION, ""
    )


def job_pods(cluster, name):
    return [
        p
        for p in cluster.client.list(
            Pod, namespace=NS, labels={C.JOB_NAME_LABEL: name}
        )
        if not p.metadata.deletion_timestamp
    ]


def patch_persistent(cluster, kind, name, patch, attempts=40):
    """Scenario-driver writes must land even while a seeded bad day throws
    409/429 at everything — the fault being scripted must not eat the
    script (the test_suspend idiom)."""
    from odh_kubeflow_tpu.apimachinery import (
        ConflictError,
        TooManyRequestsError,
    )

    for i in range(attempts):
        try:
            cluster.client.patch(kind, NS, name, patch)
            return
        except (ConflictError, TooManyRequestsError):
            if i == attempts - 1:
                raise
            time.sleep(0.02)


def stop_nb(cluster, name):
    # the culler's atomic stamp: stop + checkpointing ride one patch
    patch_persistent(cluster, Notebook, name, {"metadata": {"annotations": {
        C.STOP_ANNOTATION: "2026-01-01T00:00:00Z",
        C.TPU_SUSPEND_STATE_ANNOTATION: "checkpointing",
    }}})


def events(cluster, reason):
    return [
        e for e in cluster.client.list(Event, namespace=NS)
        if e.reason == reason
    ]


# ---------------------------------------------------------------------------
# admission + completion
# ---------------------------------------------------------------------------


def test_anakin_job_runs_to_succeeded(env):
    """The happy path end to end: gang admission, every host ready, cadence
    checkpoints banking acked steps, Succeeded at steps*completions — and
    the slice fully released (replicas 0, pods gone) afterwards."""
    cluster, mgr, acked = env
    ok0 = JM.tpu_jobs_total.value(result="succeeded")
    cluster.client.create(mk_job("rl-a", steps=90))
    wait_for(lambda: job_state(cluster, "rl-a") == "running", msg="running")
    wait_for(lambda: get_job(cluster, "rl-a").status.phase == "Succeeded",
             timeout=40, msg="succeeded")
    job = get_job(cluster, "rl-a")
    assert job.status.completed_steps >= 90
    # terminal park: replicas scaled away, no pods left behind
    wait_for(lambda: not job_pods(cluster, "rl-a"), msg="pods torn down")
    sts = cluster.client.get(StatefulSet, NS, "rl-a-learner")
    assert sts.spec.replicas == 0
    assert JM.tpu_jobs_total.value(result="succeeded") == ok0 + 1
    # the workload acked every banked step through the transport
    assert acked["rl-a"], "no checkpoint ack ever reached the workload"
    assert job.status.completed_steps in acked["rl-a"]


def test_sebulba_admission_is_atomic(env):
    """A sebulba job secures BOTH gangs or neither: with one warm slice and
    zero free capacity the learner's warm claim must unwind (back to warm,
    unclaimed) and no workload may exist; once a second slice frees, both
    gangs admit together."""
    cluster, mgr, acked = env
    pool = SlicePool(cluster.client)
    # nb1 occupies slice 1; stopping it releases slice 1 warm — ONE warm
    # slice in a 2-slice cluster whose other slice nb2 keeps occupied
    cluster.client.create(mk_nb("nb1"))
    cluster.client.create(mk_nb("nb2"))
    wait_for(
        lambda: sum(
            1 for p in cluster.client.list(Pod, namespace=NS)
            if p.is_ready()
        ) >= 2,
        msg="notebooks up",
    )
    stop_nb(cluster, "nb1")
    wait_for(lambda: any(e.state == "warm" for e in pool.entries()),
             msg="warm slice")

    job = mk_job("sebulba", steps=60, layout=LAYOUT_SEBULBA,
                 actors=TPUSpec(accelerator="v5e", topology="2x2"))
    cluster.client.create(job)
    # the actor gang has nowhere to go: admission must keep unwinding —
    # the warm slice stays warm (not leaked claimed) and nothing is created
    time.sleep(1.5)
    assert job_state(cluster, "sebulba") == ""
    assert not job_pods(cluster, "sebulba")
    entries = pool.entries()
    assert entries and all(e.state == "warm" for e in entries), \
        "partial sebulba admission leaked a claim"
    qcond = next(
        (c for c in get_job(cluster, "sebulba").status.conditions
         if c.type == C.JOB_QUEUED_CONDITION),
        None,
    )
    assert qcond is not None and qcond.status == "True"

    # free the second slice: both gangs must now admit together
    cluster.client.delete(Notebook, NS, "nb2")
    wait_for(lambda: job_state(cluster, "sebulba") == "running", timeout=40,
             msg="sebulba running")
    gangs = {p.metadata.labels.get(C.JOB_GANG_LABEL)
             for p in job_pods(cluster, "sebulba")}
    assert gangs == {C.JOB_GANG_LEARNER, C.JOB_GANG_ACTORS}
    wait_for(lambda: job_state(cluster, "sebulba") == "succeeded",
             timeout=40, msg="sebulba succeeded")


def test_warm_claim_fast_start():
    """A suspended notebook's released slice is a batch job's fast start:
    in a one-slice cluster the job can only admit through the warm pool,
    under its own claim key."""
    cluster, mgr, acked = build_env(slices=1)
    try:
        cluster.client.create(mk_nb("nb"))
        wait_for(
            lambda: any(p.is_ready()
                        for p in cluster.client.list(Pod, namespace=NS)),
            msg="notebook up",
        )
        stop_nb(cluster, "nb")
        pool = SlicePool(cluster.client)
        wait_for(lambda: any(e.state == "warm" for e in pool.entries()),
                 msg="warm slice")
        cluster.client.create(mk_job("rl-w", steps=60))
        wait_for(lambda: job_state(cluster, "rl-w") == "running",
                 msg="running off the warm claim")
        admitted = events(cluster, "JobAdmitted")
        assert admitted and "warm claim" in admitted[-1].message
        wait_for(lambda: job_state(cluster, "rl-w") == "succeeded",
                 timeout=40, msg="succeeded")
    finally:
        mgr.stop()
        cluster.stop()
        cluster.faults.clear()


def test_over_budget_job_queues_with_condition():
    """Demand past CHIP_BUDGET queues with QueuedOverBudget — the job must
    not reclaim anything and must create no workload while it waits."""
    cluster, mgr, acked = build_env(
        config=replace(FAST, chip_budget=4), slices=2
    )
    try:
        cluster.client.create(mk_nb("nb"))
        wait_for(
            lambda: any(p.is_ready()
                        for p in cluster.client.list(Pod, namespace=NS)),
            msg="notebook up",
        )
        cluster.client.create(mk_job("rl-q", steps=60))
        wait_for(lambda: events(cluster, "JobQueuedOverBudget"),
                 msg="queued event")
        assert job_state(cluster, "rl-q") == ""
        assert not job_pods(cluster, "rl-q")
        # the running notebook was never victimized for over-budget demand
        nb = cluster.client.get(Notebook, NS, "nb")
        assert not nb.metadata.annotations.get(C.TPU_SUSPEND_STATE_ANNOTATION)
    finally:
        mgr.stop()
        cluster.stop()
        cluster.faults.clear()


def test_free_slice_admission_reserves_through_the_pool():
    """Free-slice gang admission must RESERVE, not count: the pool is
    parked and claimed under the job's key via the lead-node CAS, so two
    jobs racing for the same free slice resolve at the claim — the loser's
    admission fails cleanly instead of both admitting and one wedging
    unbound in Admitted (the check-then-act hole a bare free-pool count
    would leave open, fatal for a pair of sebulba jobs)."""
    cluster = SimCluster().start()
    try:
        cluster.add_tpu_pool("v5e", "v5e", "2x2", slices=1)
        ctrl = TPUJobReconciler(Manager(cluster.store), FAST)
        pool = SlicePool(cluster.client)
        a, b = mk_job("race-a"), mk_job("race-b")
        ok_a, claims_a = ctrl._secure_gangs(a, job_gangs(a), f"{NS}/race-a")
        assert ok_a and claims_a
        entries = pool.entries()
        assert [e.claimed_by for e in entries] == [f"{NS}/race-a"]
        # the second job sees a CLAIMED pool, not a free one — no double
        # admission off one slice
        ok_b, _ = ctrl._secure_gangs(b, job_gangs(b), f"{NS}/race-b")
        assert not ok_b
        # ...and the failed pass left no residue: the winner's claim is
        # intact and nothing else got parked
        entries = pool.entries()
        assert [e.claimed_by for e in entries] == [f"{NS}/race-a"]
        # re-securing the SAME job is idempotent (restart mid-admission)
        ok_a2, claims_a2 = ctrl._secure_gangs(
            a, job_gangs(a), f"{NS}/race-a"
        )
        assert ok_a2 and claims_a2 == claims_a
    finally:
        cluster.stop()
        cluster.faults.clear()


# ---------------------------------------------------------------------------
# checkpoint-preempt-requeue
# ---------------------------------------------------------------------------


def test_reclaim_preempts_job_and_it_survives():
    """The three-class contention story on one slice: a default-priority
    batch job (-10) loses its slice to an arriving interactive notebook (0)
    through checkpoint-before-preempt, requeues, warm-claims the slice back
    when the notebook suspends, resumes from a step its workload ACKED, and
    still completes."""
    cluster, mgr, acked = build_env(
        config=replace(FAST, chip_budget=8), slices=1
    )
    try:
        pre0 = JM.tpu_job_preemptions_total.value(cause="reclaim")
        cluster.client.create(mk_job("rl-p", steps=300))
        wait_for(lambda: job_state(cluster, "rl-p") == "running",
                 msg="running")
        # the interactive user arrives: 4 + 4 = 8 chips inside budget 8,
        # zero free capacity -> the reclaimer must take the batch slice
        cluster.client.create(mk_nb("user"))
        wait_for(
            lambda: int(get_job(cluster, "rl-p").metadata.annotations.get(
                C.JOB_PREEMPTIONS_ANNOTATION, "0") or 0) >= 1,
            msg="job preempted and requeued",
        )
        assert JM.tpu_job_preemptions_total.value(cause="reclaim") > pre0
        wait_for(
            lambda: (lambda nb: nb.status.tpu is not None
                     and nb.status.tpu.mesh_ready)(
                cluster.client.get(Notebook, NS, "user")),
            timeout=40, msg="notebook on the reclaimed slice",
        )
        # ...and goes idle: the suspension hands the slice back warm and
        # the preempted job resumes from its saved step
        stop_nb(cluster, "user")
        wait_for(lambda: job_state(cluster, "rl-p") == "succeeded",
                 timeout=60, msg="job survived the preemption")
        job = get_job(cluster, "rl-p")
        resume_step = int(job.metadata.annotations.get(
            C.JOB_RESUME_STEP_ANNOTATION, "0") or 0)
        assert resume_step in acked["rl-p"], (
            f"resumed from step {resume_step} which the workload never "
            f"acked (acked: {acked['rl-p']})"
        )
        assert job.status.preemptions >= 1
        assert job.status.failures == 0, \
            "a reclaim-driven preemption must not charge backoffLimit"
    finally:
        mgr.stop()
        cluster.stop()
        cluster.faults.clear()


def test_host_preemption_mid_running_survival(env):
    """TPU host preemption mid-Running: the gang's readiness drops, the job
    parks Preempted (charging backoffLimit once — no preempt notice), the
    requeue re-places on the remaining slice, and the job completes from
    its acked checkpoint step."""
    cluster, mgr, acked = env
    host0 = JM.tpu_job_preemptions_total.value(cause="host-loss")
    cluster.client.create(mk_job("rl-h", steps=300))
    wait_for(lambda: job_state(cluster, "rl-h") == "running", msg="running")
    wait_for(lambda: acked.get("rl-h"), msg="first checkpoint banked")
    victim_node = job_pods(cluster, "rl-h")[0].spec.node_name
    cluster.preempt_node(victim_node, grace_s=0.1)
    wait_for(
        lambda: int(get_job(cluster, "rl-h").metadata.annotations.get(
            C.JOB_PREEMPTIONS_ANNOTATION, "0") or 0) >= 1,
        msg="preempted + requeued",
    )
    assert JM.tpu_job_preemptions_total.value(cause="host-loss") > host0
    wait_for(lambda: job_state(cluster, "rl-h") == "succeeded", timeout=60,
             msg="job survived host preemption")
    job = get_job(cluster, "rl-h")
    resume_step = int(job.metadata.annotations.get(
        C.JOB_RESUME_STEP_ANNOTATION, "0") or 0)
    assert resume_step in acked["rl-h"]
    assert job.status.failures >= 1, \
        "an unexplained host loss must charge backoffLimit"
    cluster.restore_node(victim_node)


def test_preempted_slice_parks_warm_at_job_priority():
    """ISSUE 10 bugfix sweep: a non-reclaim preemption parks the job's
    slice warm at the JOB's priority — a priority-0 park would make it the
    first idle-reclaim victim, defeating the fast requeue."""
    cluster, mgr, acked = build_env(
        # a long requeue backoff freezes the Preempted->Pending window so
        # the parked pool entry can be inspected before the re-claim
        config=replace(FAST, job_requeue_backoff_s=30.0), slices=1
    )
    try:
        cluster.client.create(mk_job("rl-park", steps=300, priority=-5))
        wait_for(lambda: job_state(cluster, "rl-park") == "running",
                 msg="running")
        cluster.client.patch(TPUJob, NS, "rl-park", {"metadata": {
            "annotations": {C.JOB_PREEMPT_ANNOTATION: "user"}}})
        wait_for(
            lambda: job_state(cluster, "rl-park") in ("preempted", ""),
            msg="parked",
        )
        pool = SlicePool(cluster.client)
        wait_for(lambda: any(e.state == "warm" for e in pool.entries()),
                 msg="slice released warm")
        entry = next(e for e in pool.entries() if e.state == "warm")
        assert entry.priority == -5, (
            f"preempted job's slice parked at priority {entry.priority}, "
            "not the job's own -5"
        )
    finally:
        mgr.stop()
        cluster.stop()
        cluster.faults.clear()


def test_checkpointing_job_never_victimized():
    """ISSUE 10 bugfix sweep (the Draining rule's mirror): the reclaimer
    must never stamp a preempt onto a job mid-Checkpointing — its save is
    exactly what makes the preemption survivable."""
    cluster = SimCluster().start()
    try:
        cluster.add_tpu_pool("v5e", "v5e", "2x2", slices=1)
        mgr = Manager(cluster.store)
        suspend = SuspendResumeController(mgr, FAST)

        def park(name, *states):
            # walk the machine legally (INVCHECK judges every write):
            # Pending -> Admitted -> Running (-> Checkpointing)
            cluster.client.create(mk_job(name))
            for state in states:
                cluster.client.patch(TPUJob, NS, name, {"metadata": {
                    "annotations": {C.JOB_STATE_ANNOTATION: state}}})

        park("mid-window", "admitted", "running", "checkpointing")
        shape = plan_slice("v5e", "2x2")
        assert suspend._pick_job_victim(mk_nb("user"), shape) is None

        park("fair-game", "admitted", "running")
        victim = suspend._pick_job_victim(mk_nb("user"), shape)
        assert victim is not None and victim.metadata.name == "fair-game"
    finally:
        cluster.stop()
        cluster.faults.clear()


# ---------------------------------------------------------------------------
# failure semantics
# ---------------------------------------------------------------------------


def test_max_runtime_fails_with_incident(env):
    """maxRuntime is a hard wallclock cap: Failed is terminal, mirrored to
    status, counted, and snapshotted as an incident bundle."""
    cluster, mgr, acked = env
    fail0 = JM.tpu_jobs_total.value(result="failed")
    cluster.client.create(mk_job("rl-f", steps=100000, max_runtime_s=2.0))
    # the terminal side effects (annotation, mirror, counter, incident)
    # land in sequence inside _fail — wait on each, don't race them
    wait_for(lambda: get_job(cluster, "rl-f").status.phase == "Failed",
             timeout=30, msg="failed on maxRuntime")
    assert job_state(cluster, "rl-f") == "failed"
    wait_for(lambda: JM.tpu_jobs_total.value(result="failed") == fail0 + 1,
             msg="failed counted")
    wait_for(
        lambda: any(
            i["reason"] == "job-failed" and i["subject"] == f"{NS}/rl-f"
            for i in recorder.incidents()
        ),
        msg="Failed must leave an incident bundle",
    )
    wait_for(lambda: not job_pods(cluster, "rl-f"), msg="pods torn down")


# ---------------------------------------------------------------------------
# the shared claim-owner table (ISSUE 10 satellite refactor)
# ---------------------------------------------------------------------------


def test_pod_claim_owner_table():
    """The scheduler's claimed-pool owner check is one shared table across
    all three workload classes — a pod names its owner through exactly one
    of the class labels, and an owner-less pod never resolves."""
    assert claim_owner_labels() == (
        C.NOTEBOOK_NAME_LABEL, C.INFERENCE_NAME_LABEL, C.JOB_NAME_LABEL,
    )
    for label, owner in (
        (C.NOTEBOOK_NAME_LABEL, "nb"),
        (C.INFERENCE_NAME_LABEL, "ep"),
        (C.JOB_NAME_LABEL, "rl"),
    ):
        pod = Pod()
        pod.metadata.namespace = "ns"
        pod.metadata.labels[label] = owner
        assert pod_claim_owner(pod) == f"ns/{owner}"
        # the static scheduler hook is the same table
        assert Scheduler._pod_owner(pod) == f"ns/{owner}"
    bare = Pod()
    bare.metadata.namespace = "ns"
    assert pod_claim_owner(bare) == ""


# ---------------------------------------------------------------------------
# seeded mixed bad day (ISSUE 10 acceptance: nothing silently stuck)
# ---------------------------------------------------------------------------


def _mixed_bad_day(seed):
    """Jobs + notebook churn + a control-plane bad day + a host preemption
    mid-Running in one 3-slice cluster: at the end every job must have
    SUCCEEDED — none stuck in Admitted/Preempted with every actor idle —
    and every survived preemption must have resumed from an acked step."""
    cluster, mgr, acked = build_env(slices=3)
    try:
        jobs = ["soak-0", "soak-1"]
        for name in jobs:
            cluster.client.create(mk_job(name, steps=240))
        cluster.client.create(mk_nb("churn"))
        wait_for(
            lambda: all(job_state(cluster, n) == "running" for n in jobs),
            timeout=40, msg="jobs running",
        )
        seeded_bad_day(cluster.faults, seed=seed)
        # one host preemption mid-Running, healed once the victim requeues
        # (3 slices / 3 workloads: an unhealed host would starve the churn)
        wait_for(lambda: acked.get(jobs[0]), timeout=40,
                 msg="first checkpoint banked before the preemption")
        victim_node = job_pods(cluster, jobs[0])[0].spec.node_name
        cluster.preempt_node(victim_node, grace_s=0.1)
        wait_for(
            lambda: int(get_job(cluster, jobs[0]).metadata.annotations.get(
                C.JOB_PREEMPTIONS_ANNOTATION, "0") or 0) >= 1,
            timeout=40, msg="soak victim preempted",
        )
        cluster.restore_node(victim_node)
        # interactive churn across the same capacity; only a fully-Active
        # notebook is stopped (the culler's own precondition — stamping
        # `checkpointing` mid-resume is not a legal machine transition)
        for _ in range(2):
            wait_for(
                lambda: (lambda nb: nb.status.tpu is not None
                         and nb.status.tpu.mesh_ready
                         and not nb.metadata.annotations.get(
                             C.TPU_SUSPEND_STATE_ANNOTATION))(
                    cluster.client.get(Notebook, NS, "churn")),
                timeout=40, msg="churn notebook ready",
            )
            stop_nb(cluster, "churn")
            wait_for(
                lambda: cluster.client.get(
                    Notebook, NS, "churn"
                ).metadata.annotations.get(
                    C.TPU_SUSPEND_STATE_ANNOTATION) == "suspended",
                timeout=40, msg="churn notebook suspended",
            )
            patch_persistent(cluster, Notebook, "churn", {"metadata": {
                "annotations": {C.STOP_ANNOTATION: None}}})
        # every job must converge to Succeeded: a job wedged in Admitted or
        # Preempted here is exactly the silent-stuck bug the requeue
        # contract exists to prevent
        wait_for(
            lambda: all(
                job_state(cluster, n) == "succeeded" for n in jobs
            ),
            timeout=90,
            msg="all jobs succeeded through the bad day "
            + str({n: job_state(cluster, n) for n in jobs}),
        )
        for name in jobs:
            job = get_job(cluster, name)
            if int(job.metadata.annotations.get(
                    C.JOB_PREEMPTIONS_ANNOTATION, "0") or 0):
                resume_step = int(job.metadata.annotations.get(
                    C.JOB_RESUME_STEP_ANNOTATION, "0") or 0)
                # 0 = from scratch: legal only when the preemption landed
                # before any save was BANKED (acked at the transport but
                # not yet annotated counts as unbanked — that progress is
                # exactly what "lost since the last checkpoint" means)
                assert resume_step == 0 or resume_step in acked.get(name, []), (
                    f"{name} resumed from unacked step {resume_step} "
                    f"(acked: {acked.get(name)})"
                )
    finally:
        mgr.stop()
        cluster.stop()
        cluster.faults.clear()


def test_job_mixed_bad_day_soak():
    _mixed_bad_day(seed=1007)


@pytest.mark.slow
def test_job_mixed_bad_day_soak_second_seed():
    _mixed_bad_day(seed=2814)
