"""Workload checkpoint/resume (models/checkpoint.py, orbax-backed).

SURVEY §5 checkpoint/resume at the workload level: a culled/rescheduled
slice restores the sharded train state and continues bit-exactly.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from odh_kubeflow_tpu.models import (
    TransformerConfig,
    init_params,
    latest_step,
    make_train_step,
    param_specs,
    restore_train_state,
    save_train_state,
)
from odh_kubeflow_tpu.parallel import MeshPlan, shard_batch


def _cfg():
    return TransformerConfig(
        vocab=64,
        d_model=32,
        n_layers=2,
        n_heads=2,
        d_ff=64,
        dtype=jnp.float32,
        use_flash=False,
        remat=False,
    )


def test_save_restore_resume_exact(tmp_path):
    from jax.sharding import NamedSharding

    mesh = MeshPlan.auto(8, want_tp=2, want_sp=2).build(jax.devices()[:8])
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    specs = param_specs(cfg, mesh)
    params = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs
    )
    step_fn, opt = make_train_step(cfg, mesh=mesh)
    step_fn = jax.jit(step_fn)
    opt_state = opt.init(params)
    batch = shard_batch(mesh, {"tokens": jnp.ones((4, 32), jnp.int32)})

    # two steps, checkpoint, one more step -> reference trajectory
    params, opt_state, _ = step_fn(params, opt_state, batch)
    params, opt_state, _ = step_fn(params, opt_state, batch)
    ckpt_dir = str(tmp_path / "ckpt")
    save_train_state(ckpt_dir, 2, {"params": params, "opt_state": opt_state})
    assert latest_step(ckpt_dir) == 2
    _, _, ref_loss = step_fn(params, opt_state, batch)

    # fresh process analog: new init, restore onto the SAME shardings
    fresh = init_params(jax.random.PRNGKey(42), cfg)
    fresh = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), fresh, specs
    )
    like = {"params": fresh, "opt_state": opt.init(fresh)}
    restored = restore_train_state(ckpt_dir, like, mesh=mesh)
    # shardings survive the round-trip
    leaf = restored["params"]["layers"]["wqkv"]
    assert leaf.sharding == NamedSharding(mesh, specs["layers"]["wqkv"])
    _, _, resumed_loss = step_fn(restored["params"], restored["opt_state"], batch)
    assert np.allclose(float(resumed_loss), float(ref_loss), rtol=0, atol=0)


def test_max_to_keep_prunes(tmp_path):
    mesh = MeshPlan.auto(8).build(jax.devices()[:8])
    state = {"x": jnp.arange(8.0)}
    d = str(tmp_path / "ckpt")
    for s in range(5):
        save_train_state(d, s, state, max_to_keep=2)
    assert latest_step(d) == 4
    # restoring an evicted step fails; the latest restores
    restored = restore_train_state(d, state)
    assert np.allclose(np.asarray(restored["x"]), np.arange(8.0))


@pytest.mark.slow  # compile-heavy CPU-mesh parity (minutes): run via -m slow
def test_pp_sharded_state_save_restore(tmp_path):
    """Checkpoint/resume for the PIPELINE storage layout: stage-stacked
    params sharded pp x tp x fsdp (incl. the interleaved wqkv and ZeRO
    embed shards) round-trip bit-exactly onto the same mesh."""
    from jax.sharding import NamedSharding

    from odh_kubeflow_tpu.models import (
        make_pp_train_step,
        pp_param_specs,
        to_pp_params,
    )

    mesh = MeshPlan(fsdp=2, pp=2, tp=2).build(jax.devices()[:8])
    cfg = _cfg()
    params = to_pp_params(init_params(jax.random.PRNGKey(0), cfg), 2, cfg, mesh)
    specs = pp_param_specs(cfg, mesh, 2)
    params = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs
    )
    step, opt = make_pp_train_step(cfg, mesh, n_micro=2)
    opt_state = opt.init(params)
    batch = shard_batch(mesh, {"tokens": jnp.ones((4, 16), jnp.int32)})
    params, opt_state, loss = jax.jit(step)(params, opt_state, batch)
    jax.block_until_ready(loss)

    state = {"params": params, "opt_state": opt_state}
    save_train_state(tmp_path, 1, state)
    assert latest_step(tmp_path) == 1
    restored = restore_train_state(tmp_path, state, step=1)
    r_params, r_opt = restored["params"], restored["opt_state"]
    for (pa, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(params)[0],
        jax.tree_util.tree_flatten_with_path(r_params)[0],
    ):
        assert a.sharding == b.sharding, jax.tree_util.keystr(pa)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # opt_state restored exactly (Adam moments etc.) ...
    for (pa, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(opt_state)[0],
        jax.tree_util.tree_flatten_with_path(r_opt)[0],
    ):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=jax.tree_util.keystr(pa)
        )
    # ... and a resumed step produces the SAME post-update params, which
    # depend on the restored moments (a zeroed moment would diverge here)
    p1, _, l1 = jax.jit(step)(params, opt_state, batch)
    p2, _, l2 = jax.jit(step)(r_params, r_opt, batch)
    assert float(l1) == float(l2)
    for (pa, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(p1)[0],
        jax.tree_util.tree_flatten_with_path(p2)[0],
    ):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=jax.tree_util.keystr(pa)
        )


def test_make_checkpoint_hook_saves_and_reports_step(tmp_path):
    """The probe agent's /tpu/checkpoint endpoint drives this hook during a
    checkpoint-before-evict window (controllers/slice_repair.py): it must
    save the live state and ack the step, and the saved checkpoint must
    restore exactly."""
    from odh_kubeflow_tpu.models import make_checkpoint_hook

    state = {"w": jnp.arange(8, dtype=jnp.float32), "b": jnp.float32(3.0)}
    directory = str(tmp_path / "ckpt")
    hook = make_checkpoint_hook(directory, lambda: (7, state))

    from odh_kubeflow_tpu.models import state_checksum

    out = hook()
    # the ack carries the state digest for restore-side verification
    # (ISSUE 9): the operator stores it and /tpu/restore must reproduce it
    assert out == {"step": 7, "checksum": state_checksum(state)}
    assert latest_step(directory) == 7
    restored = restore_train_state(directory, state)
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.arange(8, dtype=np.float32))
    assert float(restored["b"]) == 3.0
    assert state_checksum(restored) == out["checksum"]

    # the agent endpoint contract end-to-end: GET /tpu/checkpoint drives the
    # hook and reports {"saved": true, "step": N, "checksum": digest}; the
    # restore hook answers /tpu/restore with the same digest
    from odh_kubeflow_tpu.models import make_restore_hook
    from odh_kubeflow_tpu.probe import NotebookAgent, SimTPUMonitor

    agent = NotebookAgent(monitor=SimTPUMonitor(), checkpoint_hook=hook)
    assert agent.routes("/tpu/checkpoint") == {
        "saved": True, "step": 7, "checksum": out["checksum"],
    }
    agent.restore_hook = make_restore_hook(directory, lambda: state)
    rack = agent.routes("/tpu/restore")
    assert rack["restored"] is True and rack["step"] == 7
    assert rack["checksum"] == out["checksum"]
    agent_nohook = NotebookAgent(monitor=SimTPUMonitor())
    assert agent_nohook.routes("/tpu/checkpoint")["saved"] is False
    assert agent_nohook.routes("/tpu/restore")["restored"] is False


def test_reinitialize_after_repair_single_host_noop():
    """Single-host slices have no jax.distributed client; the post-repair
    re-init is a no-op returning (0, 1) — and is safe to call repeatedly."""
    from odh_kubeflow_tpu.parallel import reinitialize_after_repair

    assert reinitialize_after_repair() == (0, 1)
    assert reinitialize_after_repair() == (0, 1)
