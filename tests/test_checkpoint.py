"""Workload checkpoint/resume (models/checkpoint.py, orbax-backed).

SURVEY §5 checkpoint/resume at the workload level: a culled/rescheduled
slice restores the sharded train state and continues bit-exactly.
"""
import jax
import jax.numpy as jnp
import numpy as np

from odh_kubeflow_tpu.models import (
    TransformerConfig,
    init_params,
    latest_step,
    make_train_step,
    param_specs,
    restore_train_state,
    save_train_state,
)
from odh_kubeflow_tpu.parallel import MeshPlan, shard_batch


def _cfg():
    return TransformerConfig(
        vocab=64,
        d_model=32,
        n_layers=2,
        n_heads=2,
        d_ff=64,
        dtype=jnp.float32,
        use_flash=False,
        remat=False,
    )


def test_save_restore_resume_exact(tmp_path):
    from jax.sharding import NamedSharding

    mesh = MeshPlan.auto(8, want_tp=2, want_sp=2).build(jax.devices()[:8])
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    specs = param_specs(cfg, mesh)
    params = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs
    )
    step_fn, opt = make_train_step(cfg, mesh=mesh)
    step_fn = jax.jit(step_fn)
    opt_state = opt.init(params)
    batch = shard_batch(mesh, {"tokens": jnp.ones((4, 32), jnp.int32)})

    # two steps, checkpoint, one more step -> reference trajectory
    params, opt_state, _ = step_fn(params, opt_state, batch)
    params, opt_state, _ = step_fn(params, opt_state, batch)
    ckpt_dir = str(tmp_path / "ckpt")
    save_train_state(ckpt_dir, 2, {"params": params, "opt_state": opt_state})
    assert latest_step(ckpt_dir) == 2
    _, _, ref_loss = step_fn(params, opt_state, batch)

    # fresh process analog: new init, restore onto the SAME shardings
    fresh = init_params(jax.random.PRNGKey(42), cfg)
    fresh = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), fresh, specs
    )
    like = {"params": fresh, "opt_state": opt.init(fresh)}
    restored = restore_train_state(ckpt_dir, like, mesh=mesh)
    # shardings survive the round-trip
    leaf = restored["params"]["layers"]["wqkv"]
    assert leaf.sharding == NamedSharding(mesh, specs["layers"]["wqkv"])
    _, _, resumed_loss = step_fn(restored["params"], restored["opt_state"], batch)
    assert np.allclose(float(resumed_loss), float(ref_loss), rtol=0, atol=0)


def test_max_to_keep_prunes(tmp_path):
    mesh = MeshPlan.auto(8).build(jax.devices()[:8])
    state = {"x": jnp.arange(8.0)}
    d = str(tmp_path / "ckpt")
    for s in range(5):
        save_train_state(d, s, state, max_to_keep=2)
    assert latest_step(d) == 4
    # restoring an evicted step fails; the latest restores
    restored = restore_train_state(d, state)
    assert np.allclose(np.asarray(restored["x"]), np.arange(8.0))
