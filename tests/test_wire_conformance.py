"""Golden-transcript wire conformance: RemoteStore against CANNED
kube-apiserver exchanges.

Every other transport test runs the in-tree client against the in-tree
ApiServer — self-consistency, not Kubernetes compatibility: a shared
misunderstanding of the protocol would pass on both sides. This tier pins
the CLIENT side independently: a scripted HTTP server plays back responses
shaped exactly like a real kube-apiserver's (Status bodies, List envelopes,
chunked watch frames, BOOKMARK events, 410 Expired) and asserts the requests
RemoteStore emits — method, path, query string, content type, body — match
what a real apiserver would have to receive. Derived from the Kubernetes API
conventions and kube-apiserver response shapes; no k8s binaries exist in
this environment (reference boots the real thing:
odh-notebook-controller/controllers/suite_test.go:91-275).
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler
from urllib.parse import parse_qs, urlparse

import pytest

from odh_kubeflow_tpu.apimachinery import ConflictError, NotFoundError
from odh_kubeflow_tpu.cluster import RemoteStore
from odh_kubeflow_tpu.utils.httpserve import ThreadedHTTPServer, serve_in_thread, shutdown


class Exchange:
    """One scripted request->response pair."""

    def __init__(self, method, path, query=None, respond=200, body=None,
                 stream=None, content_type=None, request_check=None):
        self.method = method
        self.path = path
        self.query = query or {}
        self.respond = respond
        self.body = body
        self.stream = stream  # list of JSON-line frames for watch responses
        self.content_type = content_type  # expected request Content-Type
        self.request_check = request_check  # fn(parsed_request_body)


class GoldenServer:
    """Plays a transcript in order; records mismatches instead of guessing."""

    def __init__(self, transcript):
        self.transcript = list(transcript)
        self.cursor = 0
        self.errors = []
        self.lock = threading.Lock()
        golden = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _serve(self):
                with golden.lock:
                    if golden.cursor >= len(golden.transcript):
                        golden.errors.append(
                            f"unexpected extra request {self.command} {self.path}"
                        )
                        self.send_response(500)
                        self.send_header("Content-Length", "0")
                        self.end_headers()
                        return
                    ex = golden.transcript[golden.cursor]
                    golden.cursor += 1
                parsed = urlparse(self.path)
                query = {k: v[-1] for k, v in parse_qs(parsed.query).items()}
                if self.command != ex.method or parsed.path != ex.path:
                    golden.errors.append(
                        f"expected {ex.method} {ex.path}, got {self.command} {parsed.path}"
                    )
                if query != ex.query:
                    golden.errors.append(
                        f"{ex.method} {ex.path}: expected query {ex.query}, got {query}"
                    )
                if ex.content_type is not None:
                    got_ct = self.headers.get("Content-Type", "")
                    if got_ct != ex.content_type:
                        golden.errors.append(
                            f"{ex.method} {ex.path}: expected Content-Type "
                            f"{ex.content_type}, got {got_ct}"
                        )
                length = int(self.headers.get("Content-Length", "0"))
                raw = self.rfile.read(length) if length else b""
                if ex.request_check is not None:
                    try:
                        ex.request_check(json.loads(raw))
                    except AssertionError as e:
                        golden.errors.append(f"{ex.method} {ex.path}: body check: {e}")

                if ex.stream is not None:
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                    for frame in ex.stream:
                        payload = (json.dumps(frame) + "\n").encode()
                        self.wfile.write(
                            f"{len(payload):x}\r\n".encode() + payload + b"\r\n"
                        )
                        self.wfile.flush()
                    self.wfile.write(b"0\r\n\r\n")
                    self.close_connection = True
                    return
                payload = json.dumps(ex.body or {}).encode()
                self.send_response(ex.respond)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            do_GET = do_POST = do_PUT = do_PATCH = do_DELETE = _serve

        self.httpd = ThreadedHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = serve_in_thread(self.httpd, "golden-apiserver")

    @property
    def base_url(self):
        host, port = self.httpd.server_address[:2]
        return f"http://{host}:{port}"

    def stop(self):
        shutdown(self.httpd)

    def assert_complete(self):
        assert not self.errors, "\n".join(self.errors)
        assert self.cursor == len(self.transcript), (
            f"only {self.cursor}/{len(self.transcript)} exchanges consumed"
        )


# -- golden objects, shaped like real kube-apiserver payloads --

NB_PATH = "/apis/kubeflow.org/v1beta1/namespaces/default/notebooks"


def golden_notebook(rv="43817", gen=1):
    return {
        "apiVersion": "kubeflow.org/v1beta1",
        "kind": "Notebook",
        "metadata": {
            "name": "demo",
            "namespace": "default",
            "uid": "f4c1e5a2-8f7c-4a8e-9a6d-0b1c2d3e4f50",
            "resourceVersion": rv,
            "generation": gen,
            "creationTimestamp": "2026-07-30T08:00:00Z",
            "labels": {"app": "demo"},
        },
        "spec": {"template": {"spec": {"containers": []}}},
        "status": {},
    }


def status_failure(code, reason, message):
    return {
        "kind": "Status",
        "apiVersion": "v1",
        "metadata": {},
        "status": "Failure",
        "message": message,
        "reason": reason,
        "code": code,
    }


@pytest.fixture()
def golden():
    servers = []

    def make(transcript):
        s = GoldenServer(transcript)
        servers.append(s)
        return s

    yield make
    for s in servers:
        s.stop()


def _store(server, **kw):
    return RemoteStore(server.base_url, timeout=5, **kw)


def test_get_list_create_paths_and_envelopes(golden):
    server = golden([
        Exchange("GET", f"{NB_PATH}/demo", body=golden_notebook()),
        Exchange(
            "GET", NB_PATH, query={"labelSelector": "app=demo"},
            body={
                "apiVersion": "kubeflow.org/v1beta1",
                "kind": "NotebookList",
                "metadata": {"resourceVersion": "43901"},
                "items": [golden_notebook()],
            },
        ),
        Exchange(
            "POST", NB_PATH, respond=201, body=golden_notebook(),
            content_type="application/json",
            request_check=lambda b: (
                # server-populated fields must NOT be sent on create
                [None for k in ("resourceVersion", "uid")
                 if k in b.get("metadata", {})] == []
            ) or (_ for _ in ()).throw(AssertionError("sent server-owned metadata")),
        ),
    ])
    remote = _store(server)
    got = remote.get_raw("kubeflow.org/v1beta1", "Notebook", "default", "demo")
    assert got["metadata"]["uid"].startswith("f4c1e5a2")
    items, rv = remote.list_raw_with_rv(
        "kubeflow.org/v1beta1", "Notebook", namespace="default",
        label_selector={"app": "demo"},
    )
    assert rv == "43901" and len(items) == 1
    created = remote.create_raw({
        "apiVersion": "kubeflow.org/v1beta1",
        "kind": "Notebook",
        "metadata": {"name": "demo", "namespace": "default"},
        "spec": {},
    })
    assert created["metadata"]["resourceVersion"] == "43817"
    server.assert_complete()


def test_conflict_and_notfound_status_bodies(golden):
    server = golden([
        Exchange(
            "PUT", f"{NB_PATH}/demo", respond=409,
            body=status_failure(
                409, "Conflict",
                'Operation cannot be fulfilled on notebooks.kubeflow.org "demo": '
                "the object has been modified; please apply your changes to the "
                "latest version and try again",
            ),
        ),
        Exchange(
            "GET", f"{NB_PATH}/missing", respond=404,
            body=status_failure(
                404, "NotFound", 'notebooks.kubeflow.org "missing" not found'
            ),
        ),
    ])
    remote = _store(server)
    with pytest.raises(ConflictError, match="object has been modified"):
        remote.update_raw(golden_notebook(rv="1"))
    with pytest.raises(NotFoundError):
        remote.get_raw("kubeflow.org/v1beta1", "Notebook", "default", "missing")
    server.assert_complete()


def test_merge_patch_content_type_and_status_subresource(golden):
    server = golden([
        Exchange(
            "PATCH", f"{NB_PATH}/demo", body=golden_notebook(rv="43818"),
            content_type="application/merge-patch+json",
            request_check=lambda b: b == {"metadata": {"annotations": {"a": "1"}}}
            or (_ for _ in ()).throw(AssertionError(f"patch body {b}")),
        ),
        Exchange(
            "PUT", f"{NB_PATH}/demo/status", body=golden_notebook(rv="43819"),
            content_type="application/json",
        ),
    ])
    remote = _store(server)
    out = remote.patch_raw(
        "kubeflow.org/v1beta1", "Notebook", "default", "demo",
        {"metadata": {"annotations": {"a": "1"}}},
    )
    assert out["metadata"]["resourceVersion"] == "43818"
    remote.update_raw(golden_notebook(), subresource="status")
    server.assert_complete()


def test_watch_stream_bookmark_and_410_relist(golden):
    """The reflector's full life cycle against canned frames: initial LIST
    establishes the RV; the watch URL carries watch=true, allowWatchBookmarks
    and that RV; a BOOKMARK advances the resume RV without surfacing an
    event; a 410 ERROR frame (Status object, exactly kube-apiserver's shape)
    forces a relist and the next watch resumes from the fresh RV."""
    updated = golden_notebook(rv="44002", gen=2)
    server = golden([
        # reflector's initial list
        Exchange("GET", NB_PATH, body={
            "apiVersion": "kubeflow.org/v1beta1",
            "kind": "NotebookList",
            "metadata": {"resourceVersion": "44000"},
            "items": [golden_notebook(rv="43990")],
        }),
        # first watch: one MODIFIED, one BOOKMARK, then a 410 ERROR frame
        Exchange(
            "GET", NB_PATH,
            query={"watch": "true", "allowWatchBookmarks": "true",
                   "resourceVersion": "44000"},
            stream=[
                {"type": "MODIFIED", "object": updated},
                {"type": "BOOKMARK", "object": {
                    "kind": "Notebook",
                    "apiVersion": "kubeflow.org/v1beta1",
                    "metadata": {"resourceVersion": "44100"},
                }},
                {"type": "ERROR", "object": status_failure(
                    410, "Expired",
                    "too old resource version: 44100 (44200)",
                )},
            ],
        ),
        # 410 recovery: relist...
        Exchange("GET", NB_PATH, body={
            "apiVersion": "kubeflow.org/v1beta1",
            "kind": "NotebookList",
            "metadata": {"resourceVersion": "44300"},
            "items": [golden_notebook(rv="44250", gen=3)],
        }),
        # ...then resume the watch from the relisted RV
        Exchange(
            "GET", NB_PATH,
            query={"watch": "true", "allowWatchBookmarks": "true",
                   "resourceVersion": "44300"},
            stream=[{"type": "DELETED", "object": golden_notebook(rv="44400")}],
        ),
    ])
    remote = _store(server)
    w = remote.watch("kubeflow.org/v1beta1", "Notebook", namespace="default")
    try:
        first = w.get(timeout=5)
        assert first.type == "ADDED"  # initial snapshot
        ev = w.get(timeout=5)
        assert ev.type == "MODIFIED"
        assert ev.object["metadata"]["generation"] == 2
        # BOOKMARK advanced the RV silently; the 410 triggered a relist whose
        # diff re-surfaces the (changed) object as ADDED
        ev = w.get(timeout=5)
        assert ev.type == "ADDED"
        assert ev.object["metadata"]["generation"] == 3
        ev = w.get(timeout=5)
        assert ev.type == "DELETED"
    finally:
        w.stop()
    server.assert_complete()


def test_client_side_throttle_blocks_excess_requests(golden):
    """QPS/burst token bucket (client-go rate-limiter analog): a burst of
    GETs beyond `burst` must wait ~1/qps each, and the throttle reports the
    waits it imposed."""
    import time as _time

    n = 6
    server = golden([
        Exchange("GET", f"{NB_PATH}/demo", body=golden_notebook())
        for _ in range(n)
    ])
    remote = _store(server, qps=50.0, burst=2)
    t0 = _time.monotonic()
    for _ in range(n):
        remote.get_raw("kubeflow.org/v1beta1", "Notebook", "default", "demo")
    elapsed = _time.monotonic() - t0
    # 2 tokens free, 4 waits of ~20ms
    assert elapsed >= 0.05, f"burst never throttled ({elapsed:.3f}s)"
    assert remote.throttle.waits >= n - 2 - 1
    server.assert_complete()


def test_status_subresource_merge_patch_path(golden):
    """The status writers' merge-PATCH lands on the STATUS SUBRESOURCE path
    with the merge-patch content type — exactly what kube-apiserver expects
    (a PATCH to the main resource would run admission and touch spec)."""
    server = golden([
        Exchange(
            "PATCH", f"{NB_PATH}/demo/status",
            content_type="application/merge-patch+json",
            body=golden_notebook(rv="43820"),
            request_check=lambda body: ("status" in body and "tpu" in body["status"])
            or (_ for _ in ()).throw(AssertionError(f"bad patch body {body}")),
        ),
    ])
    from odh_kubeflow_tpu.cluster.client import Client
    from odh_kubeflow_tpu.api.notebook import Notebook

    client = Client(_store(server))
    client.patch_status(
        Notebook, "default", "demo",
        {"tpu": {"chipsVisible": 4, "meshReady": True}},
    )
    server.assert_complete()
