"""PROFILE=1 continuous-profiler contract tests (ISSUE 15).

The fifth runtime sibling at the RACECHECK/INVCHECK/JAXGUARD/DEPLOYGUARD
bar: inert when disarmed, and when armed its accounting must hold the
invariants the bench ledger's where_time_went mines —

- phase SELF times partition the region total (sum within 10%);
- nested regions subtract from the enclosing region's self time while a
  re-entered region name (the jaxguard burst guard inside the engine's
  step-wide scope) never double-counts;
- per-consumer attribution (the timing twin of JAXGUARD's per-consumer
  compile budgets);
- jaxguard.jit reports compile time from the traced body and run time from
  the dispatch wrapper;
- HBM watermarks attribute the sampler's observations to active regions;
- the instrumentation cost of one fully-decomposed burst scope stays under
  10% of a real (tiny-model) burst;
- /debug/profile serves snapshots (?region=/?limit=, bad args = 400) and
  incident bundles carry a profiler snapshot when armed.
"""
from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

import pytest

from odh_kubeflow_tpu.utils import profiler

pytestmark = pytest.mark.profile


@pytest.fixture(autouse=True)
def _clean_profiler(monkeypatch):
    monkeypatch.delenv("PROFILE", raising=False)
    profiler.reset()
    yield
    profiler.reset()


@pytest.fixture
def armed(monkeypatch):
    monkeypatch.setenv("PROFILE", "1")


def _spin(seconds: float) -> None:
    """Busy-wait: sleep() under-delivers on loaded CI boxes and the phase
    partition test needs the time to actually be SPENT inside the frame."""
    end = time.perf_counter() + seconds
    while time.perf_counter() < end:
        pass


# ---------------------------------------------------------------------------
# disarmed inertness
# ---------------------------------------------------------------------------


def test_disarmed_region_and_phase_touch_no_state():
    with profiler.region("serving.decode_burst"):
        with profiler.phase("admit"):
            pass
    snap = profiler.snapshot()
    assert snap["enabled"] is False
    assert snap["regions"] == {}
    assert snap["spans"] == {}


def test_disarmed_hbm_feed_is_dropped():
    profiler.on_device_memory(1e9, limit_bytes=2e9)
    assert profiler.hbm_stats() == {
        "peak_bytes": None, "limit_bytes": None, "headroom_bytes": None,
    }


def test_region_rejects_undeclared_names():
    with pytest.raises(KeyError):
        profiler.region("serving.typo")


# ---------------------------------------------------------------------------
# the where_time_went accounting invariants
# ---------------------------------------------------------------------------


def test_phase_self_times_partition_region_total(armed):
    with profiler.region("serving.decode_burst"):
        with profiler.phase("admit"):
            _spin(0.02)
            with profiler.phase("prefill"):
                _spin(0.02)
        with profiler.phase("scan"):
            _spin(0.03)
        with profiler.phase("batched_drain"):
            _spin(0.01)
    s = profiler.snapshot()["regions"]["serving.decode_burst"]
    total = s["total_s"]
    phase_self = sum(p["self_s"] for p in s["phases"].values())
    assert abs(phase_self - total) / total < 0.10, (
        f"phase self sum {phase_self:.4f}s vs region total {total:.4f}s"
    )
    # nested phase subtracts from the parent PHASE's self, not the region
    admit = s["phases"]["admit"]
    prefill = s["phases"]["prefill"]
    assert admit["total_s"] >= 0.04 - 0.005
    assert admit["self_s"] == pytest.approx(0.02, abs=0.01)
    assert prefill["self_s"] == pytest.approx(0.02, abs=0.01)


def test_reentered_region_name_does_not_double_count(armed):
    # the engine wraps its whole step in serving.decode_burst; the jaxguard
    # burst guard inside enters the SAME name — one entry must be counted
    with profiler.region("serving.decode_burst"):
        with profiler.region("serving.decode_burst"):
            _spin(0.005)
    s = profiler.snapshot()["regions"]["serving.decode_burst"]
    assert s["count"] == 1


def test_nested_region_subtracts_from_enclosing_self(armed):
    with profiler.region("serving.decode_burst"):
        _spin(0.01)
        with profiler.region("serving.prefill"):
            _spin(0.02)
    regions = profiler.snapshot()["regions"]
    burst, prefill = regions["serving.decode_burst"], regions["serving.prefill"]
    assert prefill["total_s"] >= 0.02 - 0.002
    # the enclosing region's SELF excludes the nested region's time...
    assert burst["self_s"] == pytest.approx(0.01, abs=0.008)
    # ...but its TOTAL keeps it (self/total is the flame-graph split)
    assert burst["total_s"] >= burst["self_s"] + prefill["total_s"] - 0.002


def test_per_consumer_attribution(armed):
    for consumer, n in (("engine-a", 2), ("engine-b", 3)):
        for _ in range(n):
            with profiler.region("serving.decode_burst", consumer=consumer):
                _spin(0.001)
    cons = profiler.snapshot()["regions"]["serving.decode_burst"]["consumers"]
    assert cons["engine-a"]["count"] == 2
    assert cons["engine-b"]["count"] == 3
    assert cons["engine-b"]["total_s"] > 0


def test_snapshot_region_filter_and_top_n_limit(armed):
    with profiler.region("serving.decode_burst"):
        _spin(0.005)
    with profiler.region("bench.train_step"):
        _spin(0.001)
    snap = profiler.snapshot(region="bench.train_step")
    assert list(snap["regions"]) == ["bench.train_step"]
    # top-N orders by self time: the burst spun longer
    snap = profiler.snapshot(limit=1)
    assert list(snap["regions"]) == ["serving.decode_burst"]


# ---------------------------------------------------------------------------
# jaxguard integration: compile/run split + the armed engine
# ---------------------------------------------------------------------------


def test_jaxguard_jit_reports_compile_and_run_time(armed):
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    from odh_kubeflow_tpu.utils import jaxguard

    def mul(x, n):
        return x * n

    f = jaxguard.jit(mul, region="bench.train_step", static_argnums=(1,))
    f(jnp.ones(4), 2)
    f(jnp.ones(4), 2)  # cache hit: run, no compile
    f(jnp.ones(4), 3)  # retrace
    jax.block_until_ready(f(jnp.ones(4), 3))
    s = profiler.snapshot()["regions"]["bench.train_step"]
    assert s["compiles"] == 2
    assert s["compile_s"] > 0
    assert s["jit_calls"] == 4
    assert s["jit_run_s"] > 0


def test_jaxguard_jit_records_nothing_disarmed():
    pytest.importorskip("jax")
    import jax.numpy as jnp

    from odh_kubeflow_tpu.utils import jaxguard

    f = jaxguard.jit(lambda x: x + 1, region="bench.train_step")
    f(jnp.ones(4))
    assert profiler.snapshot()["regions"] == {}


def test_engine_step_decomposes_into_phases(armed):
    """The acceptance shape: one engine episode under PROFILE=1 yields a
    serving.decode_burst region whose admit/prefill/scan/batched_drain/emit
    phase self times sum to within 10% of the region total."""
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    from odh_kubeflow_tpu.models import TransformerConfig, init_params
    from odh_kubeflow_tpu.serving.engine import ServingEngine

    cfg = TransformerConfig(
        vocab=97, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=128,
        max_seq=64, dtype=jnp.float32, use_flash=False, remat=False,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    eng = ServingEngine(params, cfg, max_slots=2, max_seq=64)
    handles = [eng.submit([1, 2, 3], max_new=6) for _ in range(3)]
    assert eng.run_until_idle(timeout=120)
    assert all(h.result == "ok" for h in handles)

    s = profiler.snapshot()["regions"]["serving.decode_burst"]
    assert s["count"] > 0
    for phase_name in ("admit", "prefill", "scan", "batched_drain", "emit"):
        assert phase_name in s["phases"], phase_name
    phase_self = sum(p["self_s"] for p in s["phases"].values())
    assert abs(phase_self - s["total_s"]) / s["total_s"] < 0.10
    # the nested prefill region reported under its own name too
    assert "serving.prefill" in profiler.snapshot()["regions"]
    # ...and the ledger mines the same snapshot into where_time_went
    from bench import ledger

    wtw = ledger.where_time_went()
    assert wtw["serving.decode_burst"]["coverage"] >= 0.9


# ---------------------------------------------------------------------------
# HBM watermarks
# ---------------------------------------------------------------------------


def test_hbm_watermark_attributes_to_active_regions(armed):
    frame = profiler.region_enter("serving.decode_burst")
    try:
        profiler.on_device_memory(5e8)
        profiler.on_device_memory(9e8, limit_bytes=16e8)
        profiler.on_device_memory(7e8)  # below peak: no regression
    finally:
        profiler.region_exit(frame)
    profiler.on_device_memory(11e8)  # no region active: global mark only
    snap = profiler.snapshot()
    assert snap["regions"]["serving.decode_burst"]["hbm_peak_bytes"] == 9e8
    assert snap["hbm"] == {
        "peak_bytes": 11e8, "limit_bytes": 16e8, "headroom_bytes": 5e8,
    }


def test_telemetry_sampler_feeds_profiler(armed):
    from odh_kubeflow_tpu.tpu import telemetry

    frame = profiler.region_enter("serving.decode_burst")
    try:
        telemetry.record_device_memory([(3e8, 5), (4e8, 7), (None, None)])
    finally:
        profiler.region_exit(frame)
    snap = profiler.snapshot()
    # max across devices is the watermark feed
    assert snap["regions"]["serving.decode_burst"]["hbm_peak_bytes"] == 4e8


# ---------------------------------------------------------------------------
# span phases (suspend/resume land in the same snapshot)
# ---------------------------------------------------------------------------


def test_completed_spans_aggregate_by_name(armed):
    from odh_kubeflow_tpu.utils import tracing

    tracing.set_enabled(True)
    tracer = tracing.Tracer("test")
    with tracer.start_span("notebook.resume"):
        _spin(0.002)
    with tracer.start_span("notebook.resume"):
        _spin(0.002)
    spans = profiler.snapshot()["spans"]
    assert spans["notebook.resume"]["count"] == 2
    assert spans["notebook.resume"]["total_s"] >= 0.003


# ---------------------------------------------------------------------------
# cost: the armed scope must be cheap relative to a real burst
# ---------------------------------------------------------------------------


def test_armed_overhead_under_ten_percent_per_burst(armed):
    """The acceptance bar: the fully-decomposed step scope (one region + the
    five phases the engine enters per burst) must cost <10% of a real burst.
    Measured against the tiny CPU model's burst time — the TPU burst is
    longer, so the bound only tightens on hardware."""
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    from odh_kubeflow_tpu.models import TransformerConfig, init_params
    from odh_kubeflow_tpu.serving.engine import ServingEngine

    cfg = TransformerConfig(
        vocab=97, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=128,
        max_seq=64, dtype=jnp.float32, use_flash=False, remat=False,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    eng = ServingEngine(params, cfg, max_slots=2, max_seq=64)
    eng.submit([1, 2, 3], max_new=8)
    burst_times = []
    while not eng.idle():
        t0 = time.perf_counter()
        eng.step()
        burst_times.append(time.perf_counter() - t0)
    burst_s = min(burst_times)

    n = 2000

    def scope_cost():
        t0 = time.perf_counter()
        for _ in range(n):
            with profiler.region("serving.decode_burst", consumer="bench"):
                with profiler.phase("admit"):
                    with profiler.phase("prefill"):
                        pass
                with profiler.phase("scan"):
                    pass
                with profiler.phase("batched_drain"):
                    pass
                with profiler.phase("emit"):
                    pass
        return (time.perf_counter() - t0) / n

    per_scope = min(scope_cost() for _ in range(3))
    # same absolute-floor idiom as the jaxguard/invcheck overhead tests:
    # 10% of a measured burst, floored to absorb CI scheduler noise
    assert per_scope < max(0.10 * burst_s, 0.0005), (
        f"profiler scope costs {per_scope * 1e6:.1f}us against a "
        f"{burst_s * 1e3:.2f}ms burst"
    )


# ---------------------------------------------------------------------------
# /debug/profile + incident bundles
# ---------------------------------------------------------------------------


class _StubManager:
    """The minimum surface ServingEndpoints asks of a manager."""

    def __init__(self):
        from odh_kubeflow_tpu.runtime.metrics import Registry

        self.metrics = Registry()

    def healthz(self) -> bool:
        return True

    def readyz(self) -> bool:
        return True


@pytest.fixture
def endpoints():
    from odh_kubeflow_tpu.runtime.serving import ServingEndpoints

    ep = ServingEndpoints(
        _StubManager(), metrics_port=0, health_port=0, host="127.0.0.1"
    ).start()
    yield ep
    ep.stop()


def _get(ep, path):
    host, port = ep.metrics_address
    with urllib.request.urlopen(f"http://{host}:{port}{path}", timeout=5) as r:
        return r.status, json.loads(r.read())


def test_debug_profile_serves_snapshot(armed, endpoints):
    with profiler.region("serving.decode_burst"):
        with profiler.phase("scan"):
            _spin(0.002)
    status, payload = _get(endpoints, "/debug/profile")
    assert status == 200
    assert payload["enabled"] is True
    assert "serving.decode_burst" in payload["regions"]
    assert "scan" in payload["regions"]["serving.decode_burst"]["phases"]
    # ?region= narrows, ?limit= truncates
    status, payload = _get(endpoints, "/debug/profile?region=bench.train_step")
    assert status == 200 and payload["regions"] == {}
    status, payload = _get(endpoints, "/debug/profile?limit=0")
    assert status == 200 and payload["regions"] == {}


def test_debug_profile_bad_args_are_400(endpoints):
    host, port = endpoints.metrics_address
    for query in ("?limit=nope", "?limit=-1", "?region=serving.typo"):
        with pytest.raises(urllib.error.HTTPError) as excinfo:
            urllib.request.urlopen(
                f"http://{host}:{port}/debug/profile{query}", timeout=5
            )
        assert excinfo.value.code == 400


def test_debug_index_links_profile(endpoints):
    host, port = endpoints.metrics_address
    with urllib.request.urlopen(f"http://{host}:{port}/debug/", timeout=5) as r:
        body = r.read().decode()
    assert "/debug/profile" in body


def test_incident_bundle_carries_profile_snapshot(armed):
    from odh_kubeflow_tpu.runtime.flightrecorder import FlightRecorder

    with profiler.region("serving.decode_burst"):
        with profiler.phase("scan"):
            _spin(0.002)
    rec = FlightRecorder()
    rec.record("slice.degraded", notebook="ns/nb", cause="test")
    incident_id = rec.snapshot("decode-latency", subject="ns/nb")
    bundle = rec.get(incident_id)
    assert "profile" in bundle
    assert "serving.decode_burst" in bundle["profile"]["regions"]


def test_incident_bundle_omits_profile_when_disarmed():
    from odh_kubeflow_tpu.runtime.flightrecorder import FlightRecorder

    rec = FlightRecorder()
    rec.record("slice.degraded", notebook="ns/nb", cause="test")
    bundle = rec.get(rec.snapshot("decode-latency", subject="ns/nb"))
    assert "profile" not in bundle
