"""Suspend/resume + warm slice pools (ISSUE 7): cull→checkpoint→pool-release,
warm-hit resume, pool-miss cold fallback, priority-based reclaim under
oversubscription, and the seeded churn soak asserting no notebook is ever
silently stuck in Resuming.

Deterministic tier-1 tests (marker: suspend); ci/faults.sh reruns the churn
soak in its pool-churn lane (REPEAT iterations + RACECHECK=1).
"""
import time

import pytest

from odh_kubeflow_tpu.api.core import Container, Event, Node, Pod
from odh_kubeflow_tpu.api.notebook import Notebook, TPUSpec
from odh_kubeflow_tpu.cluster import SimCluster, SlicePool, seeded_pool_bad_day
from odh_kubeflow_tpu.cluster.slicepool import (
    POOL_STATE_ANNOTATION,
    POOL_STATE_WARM,
    notebook_reclaims_total,
    notebook_resume_seconds,
    slice_pool_hits_total,
    slice_pool_misses_total,
)
from odh_kubeflow_tpu.controllers import (
    Config,
    CullingReconciler,
    NotebookReconciler,
    ProbeStatusController,
    SuspendResumeController,
    constants as C,
)
from odh_kubeflow_tpu.probe import sim_agent_behavior
from odh_kubeflow_tpu.runtime import Manager
from odh_kubeflow_tpu.runtime.flightrecorder import recorder
from odh_kubeflow_tpu.tpu import GKE_NODEPOOL_LABEL

pytestmark = pytest.mark.suspend

NS = "multiplex"

FAST = Config(
    enable_culling=True,
    suspend_enabled=True,
    cull_idle_time_min=1.0 / 60.0,  # 1.0 s idle threshold
    idleness_check_period_min=0.1 / 60.0,
    readiness_probe_period_s=0.15,
    suspend_checkpoint_window_s=1.5,
    suspend_checkpoint_retries=2,
    suspend_checkpoint_backoff_s=0.05,
    resume_timeout_s=20.0,
    resume_max_attempts=4,
    reclaim_pending_grace_s=0.3,
)


def build_env(config=FAST, slices=2, duty=0.9, kernels_busy=True):
    cluster = SimCluster().start()
    cluster.add_tpu_pool("v5e", "v5e", "2x2", slices=slices)
    mgr = Manager(cluster.store)
    NotebookReconciler(mgr, config).setup()
    ProbeStatusController(mgr, config, http_get=cluster.http_get).setup()
    CullingReconciler(mgr, config, http_get=cluster.http_get).setup()
    SuspendResumeController(mgr, config, http_get=cluster.http_get).setup()
    agents = {}
    cluster.add_pod_behavior(
        sim_agent_behavior(agents, duty=duty, kernels_busy=kernels_busy)
    )
    mgr.start()
    return cluster, mgr, agents


@pytest.fixture()
def env():
    # busy by default: suspension is test-triggered (idle scripting or stop)
    cluster, mgr, agents = build_env()
    yield cluster, mgr, agents
    mgr.stop()
    cluster.stop()
    cluster.faults.clear()


def mk_nb(name, priority=0, labels=None):
    nb = Notebook()
    nb.metadata.name = name
    nb.metadata.namespace = NS
    if labels:
        nb.metadata.labels.update(labels)
    nb.spec.template.spec.containers = [Container(name=name, image="jax:1")]
    nb.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2", priority=priority)
    return nb


def wait_for(fn, timeout=30, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(0.05)
    raise AssertionError(f"timeout: {msg}")


def get_nb(cluster, name):
    return cluster.client.get(Notebook, NS, name)


def suspend_state(cluster, name):
    return get_nb(cluster, name).metadata.annotations.get(
        C.TPU_SUSPEND_STATE_ANNOTATION, ""
    )


def mesh_ready(cluster, name):
    nb = get_nb(cluster, name)
    return nb.status.tpu is not None and nb.status.tpu.mesh_ready


def active(cluster, name):
    nb = get_nb(cluster, name)
    return (
        C.STOP_ANNOTATION not in nb.metadata.annotations
        and not nb.metadata.annotations.get(C.TPU_SUSPEND_STATE_ANNOTATION)
        and mesh_ready(cluster, name)
    )


def pods_of(cluster, name):
    return [
        p
        for p in cluster.client.list(
            Pod, namespace=NS, labels={C.NOTEBOOK_NAME_LABEL: name}
        )
        if not p.metadata.deletion_timestamp
    ]


def warm_pools(cluster):
    pools = set()
    for n in cluster.client.list(Node):
        if n.metadata.annotations.get(POOL_STATE_ANNOTATION) == POOL_STATE_WARM:
            pools.add(n.metadata.labels.get(GKE_NODEPOOL_LABEL))
    return pools


def patch_persistent(cluster, name, patch, attempts=40):
    """Scenario-driver writes must land even while a seeded bad day throws
    409/429 at everything (the SimCluster._retry_persistent idiom) — the
    fault being scripted must not eat the script."""
    from odh_kubeflow_tpu.apimachinery import ConflictError, TooManyRequestsError

    for i in range(attempts):
        try:
            cluster.client.patch(Notebook, NS, name, patch)
            return
        except (ConflictError, TooManyRequestsError):
            if i == attempts - 1:
                raise
            time.sleep(0.02)


def stop(cluster, name):
    """A suspend-aware stop: the checkpointing stamp rides the same patch as
    the stop annotation (exactly what the culler writes), so the scale-down
    can never race the checkpoint window."""
    patch_persistent(
        cluster, name,
        {"metadata": {"annotations": {
            C.STOP_ANNOTATION: "2026-01-01T00:00:00Z",
            C.TPU_SUSPEND_STATE_ANNOTATION: "checkpointing",
        }}},
    )


def unstop(cluster, name):
    patch_persistent(
        cluster, name,
        {"metadata": {"annotations": {C.STOP_ANNOTATION: None}}},
    )


def has_event(cluster, reason, involved=None):
    for e in cluster.client.list(Event, namespace=NS):
        if e.reason != reason:
            continue
        if involved is None or e.involved_object.name == involved:
            return True
    return False


# ---------------------------------------------------------------------------
# cull -> checkpoint -> warm pool release
# ---------------------------------------------------------------------------


def test_cull_checkpoints_and_releases_warm_pool(env):
    cluster, mgr, agents = env
    cluster.client.create(mk_nb("idler"))
    wait_for(lambda: mesh_ready(cluster, "idler"), msg="bring-up")

    hook_calls = []
    agents["idler-0"].checkpoint_hook = (
        lambda: hook_calls.append(1) or {"step": 42}
    )
    # the slice and the kernels both go quiet -> the CULLER fires, and with
    # suspend enabled its stop patch carries the checkpointing stamp
    agents["idler-0"].monitor.duty = 0.0
    agents["idler-0"].kernels.set_idle(time.time() - 3600)

    wait_for(
        lambda: suspend_state(cluster, "idler") == "suspended",
        msg="culled into Suspended",
    )
    nb = get_nb(cluster, "idler")
    # checkpoint-before-suspend contract: the hook ran and the acked step is
    # durable for the resume to restore
    assert hook_calls, "checkpoint hook never driven during the suspend window"
    assert nb.metadata.annotations.get(C.TPU_CHECKPOINT_SAVED_ANNOTATION) == "42"
    assert C.STOP_ANNOTATION in nb.metadata.annotations
    # the slice was released WARM, not torn down into general capacity
    assert warm_pools(cluster), "no warm pool entry after suspension"
    wait_for(lambda: has_event(cluster, "NotebookSuspended", "idler"),
             msg="NotebookSuspended event")
    # replicas went to 0 only after the window: pods drain now
    wait_for(lambda: not pods_of(cluster, "idler"), msg="pods gone")
    assert mgr.healthz()


# ---------------------------------------------------------------------------
# warm-hit resume (+ the idle-clock re-arm regression)
# ---------------------------------------------------------------------------


def test_warm_hit_resume_and_idle_clock_rearm(env):
    cluster, mgr, agents = env
    hits0 = slice_pool_hits_total.value()
    resumes0 = notebook_resume_seconds._totals.get((), 0)
    cluster.client.create(mk_nb("sleeper"))
    wait_for(lambda: mesh_ready(cluster, "sleeper"), msg="bring-up")
    agents["sleeper-0"].checkpoint_hook = lambda: {"step": 7}

    stop(cluster, "sleeper")
    wait_for(
        lambda: suspend_state(cluster, "sleeper") == "suspended"
        and not pods_of(cluster, "sleeper"),
        msg="suspended, slice released",
    )
    assert warm_pools(cluster)

    # the preserved pre-suspend last-activity: hours old. Without the re-arm
    # a just-resumed notebook reads as instantly cullable.
    patch_persistent(
        cluster, "sleeper",
        {"metadata": {"annotations": {
            C.LAST_ACTIVITY_ANNOTATION: "2020-01-01T00:00:00Z",
        }}},
    )

    t_unstop = time.time()
    unstop(cluster, "sleeper")
    wait_for(lambda: active(cluster, "sleeper"), msg="resumed to Active")

    # warm pool hit: the claim bound the mesh-formed slice
    assert slice_pool_hits_total.value() - hits0 >= 1
    assert notebook_resume_seconds._totals.get((), 0) - resumes0 >= 1
    # (wait_for: the event write lands one hop after the state clears)
    wait_for(lambda: has_event(cluster, "NotebookResumed", "sleeper"),
             msg="NotebookResumed event")
    nb = get_nb(cluster, "sleeper")
    # resume wound the machine fully down and UNCLAIMED the nodes
    for key in (
        C.TPU_SUSPEND_STATE_ANNOTATION,
        C.TPU_RESUME_STARTED_ANNOTATION,
        C.TPU_RESUME_ATTEMPTS_ANNOTATION,
        C.TPU_SUSPENDED_AT_ANNOTATION,
    ):
        assert key not in nb.metadata.annotations
    assert not any(
        n.metadata.annotations.get(POOL_STATE_ANNOTATION)
        for n in cluster.client.list(Node)
    ), "pool marks leaked past resume completion"
    # ISSUE 7 satellite: the idleness clock re-armed FROM RESUME TIME, not
    # the preserved 2020 annotation (wait_for: a stale culler removal patch
    # can race just past the re-arm; the next culler pass re-initializes)
    from odh_kubeflow_tpu.apimachinery import parse_time

    def rearmed():
        ts = get_nb(cluster, "sleeper").metadata.annotations.get(
            C.LAST_ACTIVITY_ANNOTATION
        )
        return bool(ts) and parse_time(ts).timestamp() >= t_unstop - 1.0

    wait_for(rearmed, timeout=10, msg="idle clock re-armed from resume time")
    # and the busy fresh agent keeps it alive: no instant re-cull
    time.sleep(1.5)
    assert C.STOP_ANNOTATION not in get_nb(cluster, "sleeper").metadata.annotations
    assert mgr.healthz()


# ---------------------------------------------------------------------------
# pool miss -> cold fallback
# ---------------------------------------------------------------------------


def test_pool_miss_falls_back_to_cold_placement(env):
    cluster, mgr, agents = env
    misses0 = slice_pool_misses_total.value()
    cluster.client.create(mk_nb("cold"))
    wait_for(lambda: mesh_ready(cluster, "cold"), msg="bring-up")
    stop(cluster, "cold")
    wait_for(
        lambda: suspend_state(cluster, "cold") == "suspended"
        and not pods_of(cluster, "cold"),
        msg="suspended",
    )

    # capacity pressure took the warm slice while the notebook slept: the
    # pool entry is reclaimed back to general capacity
    sp = SlicePool(cluster.client)
    entry = sp.reclaim_idle("tpu-v5-lite-podslice", "2x2")
    assert entry is not None, "expected an idle warm slice to reclaim"
    assert notebook_reclaims_total.value(reason="pool-idle") >= 1
    assert not warm_pools(cluster)

    unstop(cluster, "cold")
    wait_for(lambda: active(cluster, "cold"), msg="cold-fallback resume")
    assert slice_pool_misses_total.value() - misses0 >= 1
    wait_for(lambda: has_event(cluster, "NotebookResumed", "cold"),
             msg="NotebookResumed event")
    assert mgr.healthz()


# ---------------------------------------------------------------------------
# suspend aborted by the user returning mid-checkpoint
# ---------------------------------------------------------------------------


def test_user_return_mid_checkpoint_aborts_suspend():
    # a LONG window (no checkpoint hook -> no acks -> the window runs to its
    # deadline) so the user's return deterministically lands mid-checkpoint
    # even on a starved machine
    config = Config(
        enable_culling=False,
        suspend_enabled=True,
        readiness_probe_period_s=0.15,
        suspend_checkpoint_window_s=10.0,
        resume_timeout_s=8.0,
        resume_max_attempts=4,
    )
    cluster, mgr, agents = build_env(config=config)
    try:
        cluster.client.create(mk_nb("comeback"))
        wait_for(lambda: mesh_ready(cluster, "comeback"), msg="bring-up")
        stop(cluster, "comeback")
        wait_for(
            lambda: suspend_state(cluster, "comeback") == "checkpointing",
            msg="checkpoint window open",
        )
        unstop(cluster, "comeback")
        wait_for(
            lambda: suspend_state(cluster, "comeback") == ""
            and active(cluster, "comeback"),
            msg="suspend aborted, still Active",
        )
        wait_for(lambda: has_event(cluster, "SuspendAborted", "comeback"),
                 msg="SuspendAborted event")
        assert not warm_pools(cluster), (
            "aborted suspend must not release the slice"
        )
    finally:
        mgr.stop()
        cluster.stop()


# ---------------------------------------------------------------------------
# checkpoint-hook retries (satellite: one transient blip must not abort)
# ---------------------------------------------------------------------------


def test_checkpoint_survives_transient_probe_blips():
    cluster = SimCluster().start()
    cluster.add_tpu_pool("v5e", "v5e", "2x2", slices=2)
    blips = {"n": 0}

    def flaky_http_get(url, timeout=10.0):
        if "/tpu/checkpoint" in url and blips["n"] < 2:
            # the first two checkpoint calls die at the transport — the old
            # single-shot sweep would record no ack and suspend stateless
            blips["n"] += 1
            raise ConnectionError("injected transient probe blip")
        return cluster.http_get(url, timeout=timeout)

    mgr = Manager(cluster.store)
    NotebookReconciler(mgr, FAST).setup()
    ProbeStatusController(mgr, FAST, http_get=cluster.http_get).setup()
    SuspendResumeController(mgr, FAST, http_get=flaky_http_get).setup()
    agents = {}
    cluster.add_pod_behavior(sim_agent_behavior(agents, duty=0.9))
    mgr.start()
    try:
        cluster.client.create(mk_nb("flaky"))
        wait_for(lambda: mesh_ready(cluster, "flaky"), msg="bring-up")
        agents["flaky-0"].checkpoint_hook = lambda: {"step": 99}
        stop(cluster, "flaky")
        wait_for(
            lambda: suspend_state(cluster, "flaky") == "suspended",
            msg="suspended despite blips",
        )
        nb = get_nb(cluster, "flaky")
        assert blips["n"] == 2, "the transient blips never fired"
        # the retried sweep got through: the ack is durable
        assert nb.metadata.annotations.get(C.TPU_CHECKPOINT_SAVED_ANNOTATION) == "99"
    finally:
        mgr.stop()
        cluster.stop()


# ---------------------------------------------------------------------------
# priority-based reclaim under oversubscription
# ---------------------------------------------------------------------------


def test_priority_reclaim_picks_lowest_and_spares_canary():
    config = Config(
        enable_culling=False,  # reclaim drives every suspension here
        suspend_enabled=True,
        readiness_probe_period_s=0.15,
        suspend_checkpoint_window_s=1.0,
        resume_timeout_s=8.0,
        resume_max_attempts=4,
        reclaim_pending_grace_s=0.3,
    )
    cluster, mgr, agents = build_env(config=config, slices=3)
    try:
        reclaims0 = notebook_reclaims_total.value(reason="suspend")
        recorder.clear()
        # fill all three slices: low priority, mid priority, and the canary
        # (lowest priority of all, but reclaim-exempt)
        cluster.client.create(mk_nb("low", priority=1))
        cluster.client.create(mk_nb("mid", priority=5))
        cluster.client.create(
            mk_nb("canary", priority=0,
                  labels={C.TPU_RECLAIM_EXEMPT_LABEL: "true"})
        )
        for name in ("low", "mid", "canary"):
            wait_for(lambda n=name: mesh_ready(cluster, n), msg=f"{name} up")
        for name in ("low", "mid", "canary"):
            agents[f"{name}-0"].checkpoint_hook = lambda: {"step": 1}

        # a higher-priority notebook arrives into a full cluster
        cluster.client.create(mk_nb("vip", priority=10))
        wait_for(lambda: mesh_ready(cluster, "vip"), timeout=40,
                 msg="vip placed via reclaim")

        # the victim was the lowest-priority NON-EXEMPT notebook: "low", not
        # the canary (priority 0 but exempt), and never "mid"
        wait_for(
            lambda: suspend_state(cluster, "low") == "suspended",
            msg="low suspended cleanly",
        )
        low = get_nb(cluster, "low")
        assert low.metadata.annotations.get(C.TPU_RECLAIM_ANNOTATION, "").startswith(
            "capacity-pressure:"
        )
        # checkpoint-before-reclaim: state was saved before the slice moved
        assert C.TPU_CHECKPOINT_SAVED_ANNOTATION in low.metadata.annotations
        assert active(cluster, "mid"), "mid (higher priority) was touched"
        assert active(cluster, "canary"), "the canary must never be a victim"
        assert notebook_reclaims_total.value(reason="suspend") - reclaims0 >= 1
        wait_for(lambda: has_event(cluster, "NotebookReclaimed", "low"),
                 msg="NotebookReclaimed event")
        # a reclaim is an incident: the flight recorder snapshotted it
        assert any(i["reason"] == "reclaim" for i in recorder.incidents()), (
            "no reclaim incident bundle captured"
        )
        # a reclaim-forced suspend releases to GENERAL capacity (the
        # requester needed the chips), not back into the warm pool
        assert not warm_pools(cluster)
        assert mgr.healthz()
    finally:
        mgr.stop()
        cluster.stop()
        cluster.faults.clear()


# ---------------------------------------------------------------------------
# the webhook's reconciliation lock is NOT a stop
# ---------------------------------------------------------------------------


def test_reconciliation_lock_does_not_trigger_suspend():
    """The webhook stamps `kubeflow-resource-stopped =
    odh-notebook-controller-lock` at CREATE (reference idiom; the extension
    controller clears it). The suspend machine must ignore the sentinel —
    treating it as a stop ran a phantom suspend/resume episode at birth,
    polluting the pool hit ratio and the resume-latency histogram with
    bring-up time (caught by the full-operator verify drive, where the
    webhook actually runs)."""
    from odh_kubeflow_tpu.main import build_manager

    config = Config(
        enable_culling=False,
        suspend_enabled=True,
        readiness_probe_period_s=0.15,
        slo_enabled=False,
    )
    cluster = SimCluster().start()
    cluster.add_tpu_pool("v5e", "v5e", "2x2", slices=1)
    agents = {}
    cluster.add_pod_behavior(sim_agent_behavior(agents, duty=0.9))
    mgr = build_manager(cluster.store, config, http_get=cluster.http_get)
    mgr.start()
    try:
        resumes0 = notebook_resume_seconds._totals.get((), 0)
        misses0 = slice_pool_misses_total.value()
        cluster.client.create(mk_nb("fresh"))
        wait_for(lambda: mesh_ready(cluster, "fresh"), msg="bring-up")
        time.sleep(0.5)
        nb = get_nb(cluster, "fresh")
        assert not nb.metadata.annotations.get(
            C.TPU_SUSPEND_STATE_ANNOTATION
        ), "the reconciliation lock ran a phantom suspend episode"
        assert notebook_resume_seconds._totals.get((), 0) == resumes0
        assert slice_pool_misses_total.value() == misses0
    finally:
        mgr.stop()
        cluster.stop()


# ---------------------------------------------------------------------------
# ResumeFailed is terminal-but-self-healing (the RepairFailed idiom)
# ---------------------------------------------------------------------------


def test_resume_failed_is_explicit_and_self_heals():
    config = Config(
        enable_culling=False,
        suspend_enabled=True,
        readiness_probe_period_s=0.15,
        suspend_checkpoint_window_s=0.5,
        resume_timeout_s=1.2,  # tiny budget: exhaustion is the point
        resume_max_attempts=2,
        reclaim_pending_grace_s=0.3,
    )
    cluster, mgr, agents = build_env(config=config, slices=1)
    try:
        recorder.clear()
        cluster.client.create(mk_nb("trapped"))
        wait_for(lambda: mesh_ready(cluster, "trapped"), msg="bring-up")
        stop(cluster, "trapped")
        wait_for(
            lambda: suspend_state(cluster, "trapped") == "suspended"
            and not pods_of(cluster, "trapped"),
            msg="suspended",
        )
        # the ONLY slice vanishes while the notebook sleeps: nowhere to
        # resume, warm or cold
        sp = SlicePool(cluster.client)
        assert sp.reclaim_idle("tpu-v5-lite-podslice", "2x2") is not None
        nodes = [n.metadata.name for n in cluster.client.list(Node)]
        for node in nodes:
            cluster.preempt_node(node, grace_s=0.05)
        unstop(cluster, "trapped")
        # explicit terminal state, never a silent wedge
        wait_for(
            lambda: suspend_state(cluster, "trapped") == "resume-failed",
            msg="explicit ResumeFailed",
        )
        wait_for(lambda: has_event(cluster, "ResumeFailed", "trapped"),
                 msg="ResumeFailed event")
        assert any(
            i["reason"] == "resume-failed" for i in recorder.incidents()
        ), "no resume-failed incident bundle captured"
        # capacity returns -> the failed resume closes itself out
        for node in nodes:
            cluster.restore_node(node)
        wait_for(lambda: active(cluster, "trapped"), timeout=40,
                 msg="self-healed after capacity returned")
        assert mgr.healthz()
    finally:
        mgr.stop()
        cluster.stop()
        cluster.faults.clear()


# ---------------------------------------------------------------------------
# the seeded churn soak: suspend/resume/reclaim cycling under a pool bad day
# ---------------------------------------------------------------------------


def _run_pool_churn(seed, cycles=2):
    cluster, mgr, agents = build_env(slices=4)
    try:
        names = [f"churn-{i}" for i in range(3)]
        for name in names:
            cluster.client.create(mk_nb(name))
        for name in names:
            wait_for(lambda n=name: mesh_ready(cluster, n), msg=f"{name} up")
        for name in names:
            agents[f"{name}-0"].checkpoint_hook = lambda: {"step": 5}

        plan = None
        for cycle in range(cycles):
            for name in names:
                stop(cluster, name)
            for name in names:
                wait_for(
                    lambda n=name: suspend_state(cluster, n) == "suspended"
                    and not pods_of(cluster, n),
                    timeout=40, msg=f"{name} suspended (cycle {cycle})",
                )
            if cycle == 0:
                # bad day lands exactly on the warm pool: seeded poisoning of
                # warm hosts + reclaim-race conflict storms + the usual
                # control-plane schedule
                warm_nodes = [
                    n.metadata.name
                    for n in cluster.client.list(Node)
                    if n.metadata.annotations.get(POOL_STATE_ANNOTATION)
                    == POOL_STATE_WARM
                ]
                plan = seeded_pool_bad_day(cluster, seed=seed,
                                           warm_nodes=warm_nodes)
                assert plan["poisoned"], "the seeded schedule poisoned nothing"
            for name in names:
                unstop(cluster, name)
            if cycle == 0 and plan is not None:
                # maintenance ends mid-resume: poisoned hosts come back so
                # every resume can land even when the pool drained
                time.sleep(1.0)
                for node in plan["poisoned"]:
                    cluster.restore_node(node)
            # THE invariant: nobody is silently stuck in Resuming — every
            # notebook returns to Active (a ResumeFailed would also fail
            # this wait, which is the point: the soak demands zero failures)
            for name in names:
                wait_for(
                    lambda n=name: active(cluster, n),
                    timeout=60, msg=f"{name} resumed (cycle {cycle})",
                )
                assert not has_event(cluster, "ResumeFailed", name)
        assert mgr.healthz(), "a controller thread died during the churn"
    finally:
        mgr.stop()
        cluster.stop()
        cluster.faults.clear()


def test_seeded_pool_churn_no_silent_stuck():
    _run_pool_churn(seed=0x5EED)


@pytest.mark.slow
def test_pool_churn_second_seed():
    _run_pool_churn(seed=0xBADC0DE, cycles=3)


# ---------------------------------------------------------------------------
# the oversubscription acceptance soak: demand > physical chips, zero
# terminal failures, at least one reclaim incident bundle
# ---------------------------------------------------------------------------


def test_oversubscription_soak_degrades_by_suspending_not_failing():
    config = Config(
        enable_culling=False,
        suspend_enabled=True,
        readiness_probe_period_s=0.15,
        suspend_checkpoint_window_s=1.0,
        # generous resume budget: the soak asserts ZERO ResumeFailed, and a
        # starved CI machine must not manufacture one out of scheduler lag
        resume_timeout_s=30.0,
        resume_max_attempts=6,
        reclaim_pending_grace_s=0.3,
        chip_budget=24,  # 6 x v5e-4 admitted over 8 physical chips
    )
    cluster, mgr, agents = build_env(config=config, slices=2)  # 8 chips
    try:
        recorder.clear()
        # 5 notebooks x 4 chips = 20 chips demanded over 8 physical, inside
        # the 24-chip budget. Ascending priority: each arrival reclaims the
        # then-lowest.
        def settled(name):
            state = suspend_state(cluster, name)
            if state == "suspended":
                return True
            if state:
                return False
            return mesh_ready(cluster, name)

        names = [(f"nb-{i}", i + 1) for i in range(5)]
        created = []
        for name, pri in names:
            cluster.client.create(mk_nb(name, priority=pri))
            created.append(name)
            # settle between arrivals: one reclaim episode at a time, the
            # way a real trickle of users arrives — every notebook so far
            # must be running or cleanly suspended before the next lands
            for n in created:
                wait_for(lambda n=n: settled(n), timeout=60,
                         msg=f"{n} neither running nor cleanly suspended "
                             f"after {name} arrived")
            for p in pods_of(cluster, name):
                if p.metadata.name in agents:
                    agents[p.metadata.name].checkpoint_hook = (
                        lambda: {"step": 3}
                    )

        # zero terminal failures anywhere: that is the whole policy
        assert not has_event(cluster, "ResumeFailed")
        assert not has_event(cluster, "RepairFailed")
        running = [n for n, _ in names if active(cluster, n)]
        parked = [n for n, _ in names
                  if suspend_state(cluster, n) == "suspended"]
        assert len(running) + len(parked) == len(names)
        # the guaranteed shape of the cascade (exact membership of the
        # second slot can vary with drain/bind interleaving): the HIGHEST
        # priority always runs, the LOWEST is always the first one parked
        assert running, "nothing running after the cascade"
        assert "nb-4" in running, f"highest priority not running: {running}"
        assert "nb-0" in parked, f"lowest priority not parked: {parked}"
        # at least one reclaim incident bundle at /debug/incidents
        assert any(i["reason"] == "reclaim" for i in recorder.incidents())

        # a user returns: capacity freed by deleting one runner, the
        # suspended notebook resumes instead of failing
        victim_runner = running[0]
        comeback = parked[0]
        cluster.client.delete(Notebook, NS, victim_runner)
        unstop(cluster, comeback)
        wait_for(lambda: active(cluster, comeback), timeout=60,
                 msg=f"{comeback} resumed after capacity returned")
        assert not has_event(cluster, "ResumeFailed")
        assert mgr.healthz()
    finally:
        mgr.stop()
        cluster.stop()
        cluster.faults.clear()


# ---------------------------------------------------------------------------
# restore-side verification after resume (ISSUE 9 satellite)
# ---------------------------------------------------------------------------


def _restore_verify_env(restore_ack):
    """Suspend env whose transport answers /tpu/restore deterministically:
    arming per-incarnation agent hooks from a polling loop races the
    controller's one-shot resume-time verification probe (and loses on a
    fast machine) — the transport answer can't."""
    import json as _json

    cluster = SimCluster().start()
    cluster.add_tpu_pool("v5e", "v5e", "2x2", slices=2)

    def http_get(url, timeout=10.0):
        if "/tpu/restore" in url:
            return 200, _json.dumps(restore_ack).encode()
        return cluster.http_get(url, timeout=timeout)

    mgr = Manager(cluster.store)
    NotebookReconciler(mgr, FAST).setup()
    ProbeStatusController(mgr, FAST, http_get=cluster.http_get).setup()
    SuspendResumeController(mgr, FAST, http_get=http_get).setup()
    agents = {}
    cluster.add_pod_behavior(sim_agent_behavior(agents, duty=0.9))
    mgr.start()
    return cluster, mgr, agents


def _drive_verified_resume(name, saved_checksum, restore_ack):
    from odh_kubeflow_tpu.cluster.slicepool import (
        notebook_restore_verifications_total,
    )

    cluster, mgr, agents = _restore_verify_env(restore_ack)
    try:
        cluster.client.create(mk_nb(name))
        wait_for(lambda: mesh_ready(cluster, name), msg="bring-up")
        agents[f"{name}-0"].checkpoint_hook = (
            lambda: {"step": restore_ack.get("step"),
                     "checksum": saved_checksum}
        )
        stop(cluster, name)
        wait_for(
            lambda: suspend_state(cluster, name) == "suspended"
            and not pods_of(cluster, name),
            msg="suspended",
        )
        nb = get_nb(cluster, name)
        # the checkpoint ack's digest is durable on the CR
        assert nb.metadata.annotations.get(
            C.TPU_CHECKPOINT_CHECKSUM_ANNOTATION) == saved_checksum
        unstop(cluster, name)
        wait_for(lambda: active(cluster, name), msg="resumed")
        assert mgr.healthz()
        return cluster, mgr, notebook_restore_verifications_total
    except BaseException:
        mgr.stop()
        cluster.stop()
        raise


def test_resume_verifies_restored_kernel():
    ack = {"restored": True, "step": 11, "checksum": "feedface"}
    cluster, mgr, counter = _drive_verified_resume("verified", "feedface", ack)
    try:
        wait_for(lambda: has_event(cluster, "RestoreVerified", "verified"),
                 msg="RestoreVerified event")
        assert counter.value(result="ok") >= 1
        assert not has_event(cluster, "RestoreVerifyFailed", "verified")
    finally:
        mgr.stop()
        cluster.stop()


def test_resume_restore_mismatch_is_loud():
    # the restored kernel does NOT match what was saved; the resume still
    # COMPLETES (live-but-suspect beats wedged) but the mismatch is loud
    ack = {"restored": True, "step": 3, "checksum": "bbbb"}
    cluster, mgr, counter = _drive_verified_resume("tainted", "aaaa", ack)
    try:
        wait_for(lambda: has_event(cluster, "RestoreVerifyFailed", "tainted"),
                 msg="RestoreVerifyFailed event")
        assert counter.value(result="mismatch") >= 1
    finally:
        mgr.stop()
        cluster.stop()


# ---------------------------------------------------------------------------
# reclaimer vs serving endpoints (ISSUE 9 bugfix)
# ---------------------------------------------------------------------------


def _mk_ep(name, priority=0, drain_s=30.0):
    from odh_kubeflow_tpu.api.inference import InferenceEndpoint, ServingSpec
    from odh_kubeflow_tpu.api.notebook import TPUSpec as _TPUSpec

    ep = InferenceEndpoint()
    ep.metadata.name = name
    ep.metadata.namespace = NS
    ep.spec.template.spec.containers = [Container(name=name, image="serve:1")]
    ep.spec.tpu = _TPUSpec(accelerator="v5e", topology="2x2",
                           priority=priority)
    ep.spec.serving = ServingSpec(drain_timeout_s=drain_s)
    return ep


def _build_serving_env(config, slices):
    from odh_kubeflow_tpu.controllers import InferenceEndpointReconciler

    cluster = SimCluster().start()
    cluster.add_tpu_pool("v5e", "v5e", "2x2", slices=slices)
    mgr = Manager(cluster.store)
    NotebookReconciler(mgr, config).setup()
    ProbeStatusController(mgr, config, http_get=cluster.http_get).setup()
    SuspendResumeController(mgr, config, http_get=cluster.http_get).setup()
    InferenceEndpointReconciler(mgr, config, http_get=cluster.http_get).setup()
    agents = {}
    cluster.add_pod_behavior(sim_agent_behavior(agents, duty=0.9))
    mgr.start()
    return cluster, mgr, agents


def test_reclaimer_treats_endpoints_by_priority_and_spares_draining():
    """ISSUE 9 bugfix, both halves: (a) a Serving endpoint's DEFAULT
    priority sits above interactive notebooks, so the notebook is the
    victim even when the endpoint's explicit priority field is 0; (b) a
    Draining endpoint is never re-victimized mid-drain."""
    from odh_kubeflow_tpu.api.inference import InferenceEndpoint

    config = Config(
        enable_culling=False,
        suspend_enabled=True,
        readiness_probe_period_s=0.15,
        suspend_checkpoint_window_s=1.0,
        resume_timeout_s=10.0,
        resume_max_attempts=4,
        reclaim_pending_grace_s=0.3,
        serving_loading_window_s=8.0,
        serving_drain_timeout_s=30.0,  # a LONG drain: mid-drain is observable
    )
    cluster, mgr, agents = _build_serving_env(config, slices=2)
    try:
        # slice 1: a Serving endpoint with priority UNSET (defaults to
        # ENDPOINT_DEFAULT_PRIORITY=10); slice 2: an interactive notebook at
        # priority 2 — above the endpoint's raw field, below its default
        cluster.client.create(_mk_ep("live-traffic"))
        wait_for(
            lambda: cluster.client.get(InferenceEndpoint, NS, "live-traffic")
            .metadata.annotations.get(C.INFERENCE_STATE_ANNOTATION)
            == "serving",
            timeout=40, msg="endpoint Serving",
        )
        cluster.client.create(mk_nb("idler", priority=2))
        wait_for(lambda: mesh_ready(cluster, "idler"), msg="notebook up")
        agents["idler-0"].checkpoint_hook = lambda: {"step": 1}

        # a priority-5 notebook arrives into a full cluster: the victim MUST
        # be the notebook (priority 2), never the endpoint (default 10)
        cluster.client.create(mk_nb("vip", priority=5))
        wait_for(lambda: mesh_ready(cluster, "vip"), timeout=40,
                 msg="vip placed via reclaim")
        assert suspend_state(cluster, "idler") in ("checkpointing", "suspended")
        ep = cluster.client.get(InferenceEndpoint, NS, "live-traffic")
        assert ep.metadata.annotations.get(
            C.INFERENCE_STATE_ANNOTATION) == "serving", (
            "the reclaimer victimized a Serving endpoint that outranked "
            "the requester"
        )
        assert C.STOP_ANNOTATION not in ep.metadata.annotations

        # now STOP the endpoint (enters its LONG drain window) and apply
        # fresh pressure: the Draining endpoint must never be re-stamped
        wait_for(lambda: suspend_state(cluster, "idler") == "suspended",
                 timeout=40, msg="idler parked")
        cluster.client.patch(
            InferenceEndpoint, NS, "live-traffic",
            {"metadata": {"annotations": {
                C.STOP_ANNOTATION: "2026-01-01T00:00:00Z",
            }}},
        )
        wait_for(
            lambda: cluster.client.get(InferenceEndpoint, NS, "live-traffic")
            .metadata.annotations.get(C.INFERENCE_STATE_ANNOTATION)
            == "draining",
            timeout=20, msg="endpoint Draining",
        )
        cluster.client.create(mk_nb("vip2", priority=9))
        time.sleep(2.0)
        ep = cluster.client.get(InferenceEndpoint, NS, "live-traffic")
        assert ep.metadata.annotations.get(
            C.TPU_RECLAIM_ANNOTATION, "") == "", (
            "a Draining endpoint was re-victimized mid-drain"
        )
        assert mgr.healthz()
    finally:
        mgr.stop()
        cluster.stop()
        cluster.faults.clear()
