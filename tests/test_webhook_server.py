"""HTTPS admission path: apiserver -> MutatingWebhookConfiguration callout ->
AdmissionReview v1 over TLS -> JSONPatch applied -> storage.

This is the flow the reference proves with envtest + its served webhook
(odh controllers/suite_test.go:120-124,183-246; CI self-signs certs in
odh_notebook_controller_integration_test.yaml:193-201). Every test here
crosses real sockets with real TLS.
"""
import base64
import json

import pytest

from odh_kubeflow_tpu.api.admission import (
    MutatingWebhook,
    MutatingWebhookConfiguration,
    RuleWithOperations,
    WebhookClientConfig,
)
from odh_kubeflow_tpu.api.notebook import Notebook
from odh_kubeflow_tpu.apimachinery import AdmissionDeniedError
from odh_kubeflow_tpu.cluster import (
    ApiServer,
    Client,
    RemoteStore,
    Store,
    WebhookDispatcher,
)
from odh_kubeflow_tpu.controllers import Config, NotebookWebhook
from odh_kubeflow_tpu.controllers import constants as C
from odh_kubeflow_tpu.runtime.webhook_server import WebhookServer
from odh_kubeflow_tpu.utils.certs import generate_cert_dir


@pytest.fixture(scope="module")
def tls(tmp_path_factory):
    cert_dir = tmp_path_factory.mktemp("pki")
    ca, crt, key = generate_cert_dir(str(cert_dir))
    with open(ca, "rb") as f:
        ca_b64 = base64.b64encode(f.read()).decode()
    return ca, crt, key, ca_b64


@pytest.fixture()
def stack(tls):
    """Store + HTTPS webhook serving the real NotebookWebhook + ApiServer
    whose admission hook is the MutatingWebhookConfiguration dispatcher."""
    ca, crt, key, ca_b64 = tls
    store = Store()
    # the webhook's own reads (image catalog etc.) go straight to the store,
    # as the reference webhook reads through the manager's client
    wh_server = WebhookServer(certfile=crt, keyfile=key).start()
    webhook = NotebookWebhook(Client(store), Config())
    wh_server.register("/mutate-notebook-v1", webhook.handle)

    cfg = MutatingWebhookConfiguration()
    cfg.metadata.name = "notebook-mutator"
    cfg.webhooks = [
        MutatingWebhook(
            name="notebooks.kubeflow.org",
            client_config=WebhookClientConfig(
                url=f"{wh_server.base_url}/mutate-notebook-v1", ca_bundle=ca_b64
            ),
            rules=[
                RuleWithOperations(
                    operations=["CREATE", "UPDATE"],
                    api_groups=["kubeflow.org"],
                    api_versions=["*"],
                    resources=["notebooks"],
                )
            ],
        )
    ]
    Client(store).create(cfg)

    api = ApiServer(store, admission=WebhookDispatcher(store)).start()
    remote = RemoteStore(api.base_url, timeout=10)
    yield store, api, remote, wh_server
    api.stop()
    wh_server.stop()


def nb_dict(name, ns="user"):
    return {
        "apiVersion": "kubeflow.org/v1beta1",
        "kind": "Notebook",
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "template": {"spec": {"containers": [{"name": name, "image": "jax:1"}]}}
        },
    }


def test_create_through_https_webhook_injects_lock(stack):
    """The VERDICT's acceptance check: an apiserver CREATE calls the webhook
    over HTTPS and the reconciliation lock lands on the stored object."""
    _, _, remote, _ = stack
    out = remote.create_raw(nb_dict("locked"))
    assert out["metadata"]["annotations"][C.STOP_ANNOTATION] == C.RECONCILIATION_LOCK_VALUE
    stored = remote.get_raw("kubeflow.org/v1beta1", "Notebook", "user", "locked")
    assert stored["metadata"]["annotations"][C.STOP_ANNOTATION] == C.RECONCILIATION_LOCK_VALUE


def test_denial_over_https_rejects_write(stack):
    """failurePolicy=Fail + allowed=false -> the write never lands."""
    _, _, remote, _ = stack
    bad = nb_dict("badtpu")
    bad["spec"]["tpu"] = {"accelerator": "v5e", "topology": "not-a-topology"}
    with pytest.raises(AdmissionDeniedError):
        remote.create_raw(bad)
    with pytest.raises(Exception):
        remote.get_raw("kubeflow.org/v1beta1", "Notebook", "user", "badtpu")


def test_update_blocking_via_wire(stack):
    """UPDATE path carries oldObject; webhook-only drift on a running
    notebook is reverted and marked update-pending (reference
    maybeRestartRunningNotebook, notebook_webhook.go:505-564)."""
    store, _, remote, _ = stack
    remote.create_raw(nb_dict("running"))
    # mark it running (status is a subresource; then clear the lock like the
    # extension controller would, via merge patch)
    remote.patch_raw(
        "kubeflow.org/v1beta1",
        "Notebook",
        "user",
        "running",
        {"metadata": {"annotations": {C.STOP_ANNOTATION: None}}},
    )
    cur = remote.get_raw("kubeflow.org/v1beta1", "Notebook", "user", "running")
    cur["status"] = {"readyReplicas": 1}
    remote.update_raw(cur, subresource="status")
    # user UPDATE that changes only metadata, while the webhook wants to
    # change the podspec (auth sidecar) -> must be blocked + update-pending
    cur = remote.get_raw("kubeflow.org/v1beta1", "Notebook", "user", "running")
    cur["metadata"].setdefault("annotations", {})[C.INJECT_AUTH_ANNOTATION] = "true"
    out = remote.update_raw(cur)
    containers = out["spec"]["template"]["spec"]["containers"]
    assert [c["name"] for c in containers] == ["running"]  # sidecar NOT added
    assert C.UPDATE_PENDING_ANNOTATION in out["metadata"]["annotations"]


def test_failure_policy_fail_rejects_when_webhook_down(tls):
    ca, crt, key, ca_b64 = tls
    store = Store()
    cfg = MutatingWebhookConfiguration()
    cfg.metadata.name = "dead-webhook"
    cfg.webhooks = [
        MutatingWebhook(
            name="dead.example.com",
            client_config=WebhookClientConfig(
                url="https://127.0.0.1:1/mutate", ca_bundle=ca_b64
            ),
            rules=[
                RuleWithOperations(
                    operations=["*"],
                    api_groups=["kubeflow.org"],
                    api_versions=["*"],
                    resources=["notebooks"],
                )
            ],
            timeout_seconds=1,
        )
    ]
    Client(store).create(cfg)
    api = ApiServer(store, admission=WebhookDispatcher(store)).start()
    remote = RemoteStore(api.base_url, timeout=10)
    try:
        with pytest.raises(AdmissionDeniedError, match="failed calling webhook"):
            remote.create_raw(nb_dict("orphan"))
    finally:
        api.stop()


def test_failure_policy_ignore_lets_write_through(tls):
    ca, crt, key, ca_b64 = tls
    store = Store()
    cfg = MutatingWebhookConfiguration()
    cfg.metadata.name = "optional-webhook"
    cfg.webhooks = [
        MutatingWebhook(
            name="optional.example.com",
            client_config=WebhookClientConfig(url="https://127.0.0.1:1/mutate"),
            rules=[
                RuleWithOperations(
                    operations=["*"],
                    api_groups=["kubeflow.org"],
                    api_versions=["*"],
                    resources=["notebooks"],
                )
            ],
            failure_policy="Ignore",
            timeout_seconds=1,
        )
    ]
    Client(store).create(cfg)
    api = ApiServer(store, admission=WebhookDispatcher(store)).start()
    remote = RemoteStore(api.base_url, timeout=10)
    try:
        out = remote.create_raw(nb_dict("unblessed"))
        assert C.STOP_ANNOTATION not in out["metadata"].get("annotations", {})
    finally:
        api.stop()


def test_wrong_ca_is_rejected(tls, tmp_path):
    """TLS verification is real: a webhook serving a cert from a different CA
    fails the callout (failurePolicy=Fail -> write rejected)."""
    ca, crt, key, ca_b64 = tls
    other_ca, other_crt, other_key = generate_cert_dir(str(tmp_path / "rogue"))
    store = Store()
    rogue = WebhookServer(certfile=other_crt, keyfile=other_key).start()
    rogue.register("/mutate", lambda req: None)
    cfg = MutatingWebhookConfiguration()
    cfg.metadata.name = "rogue-webhook"
    cfg.webhooks = [
        MutatingWebhook(
            name="rogue.example.com",
            client_config=WebhookClientConfig(
                url=f"{rogue.base_url}/mutate", ca_bundle=ca_b64  # trusted CA != serving CA
            ),
            rules=[
                RuleWithOperations(
                    operations=["*"],
                    api_groups=["kubeflow.org"],
                    api_versions=["*"],
                    resources=["notebooks"],
                )
            ],
            timeout_seconds=2,
        )
    ]
    Client(store).create(cfg)
    api = ApiServer(store, admission=WebhookDispatcher(store)).start()
    remote = RemoteStore(api.base_url, timeout=10)
    try:
        with pytest.raises(AdmissionDeniedError, match="failed calling webhook"):
            remote.create_raw(nb_dict("mitm"))
    finally:
        api.stop()
        rogue.stop()


def test_admission_review_wire_format(tls):
    """The response is spec-shaped: uid echoed, patchType JSONPatch, patch
    base64 — what a real kube-apiserver requires."""
    import urllib.request

    ca, crt, key, _ = tls
    server = WebhookServer(certfile=crt, keyfile=key).start()
    server.register(
        "/mutate",
        lambda req: {**req.object, "metadata": {**req.object["metadata"], "labels": {"x": "y"}}},
    )
    try:
        import ssl as _ssl

        ctx = _ssl.create_default_context(cafile=ca)
        review = {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "request": {
                "uid": "uid-123",
                "operation": "CREATE",
                "object": {"apiVersion": "v1", "kind": "ConfigMap", "metadata": {"name": "x"}},
            },
        }
        req = urllib.request.Request(
            f"{server.base_url}/mutate",
            data=json.dumps(review).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=5, context=ctx) as resp:
            body = json.loads(resp.read())
        assert body["kind"] == "AdmissionReview"
        r = body["response"]
        assert r["uid"] == "uid-123" and r["allowed"] is True
        assert r["patchType"] == "JSONPatch"
        ops = json.loads(base64.b64decode(r["patch"]))
        assert {"op": "add", "path": "/metadata/labels", "value": {"x": "y"}} in ops
    finally:
        server.stop()
