"""Status-write coalescing (runtime/coalesce.py, ISSUE 13 satellite): the
notebook/endpoint/job status mirrors batch adjacent PATCHes into one write
per object per sync wave — without ever dropping owned zeros or explicit
nulls (the PR 9 omitempty contract)."""
import threading
import time

import pytest

from odh_kubeflow_tpu.api.notebook import Notebook
from odh_kubeflow_tpu.apimachinery import ForbiddenError, NotFoundError
from odh_kubeflow_tpu.cluster import Client, Store
from odh_kubeflow_tpu.runtime.coalesce import StatusCoalescer, merge_patches


class RecordingClient:
    """patch_status recorder standing in for the manager's fenced client."""

    def __init__(self, error=None):
        self.calls = []
        self.error = error

    def patch_status(self, cls, namespace, name, patch):
        self.calls.append((cls, namespace, name, patch))
        if self.error is not None:
            raise self.error


# ---------------------------------------------------------------------------
# merge semantics: zeros and nulls are values
# ---------------------------------------------------------------------------


def test_merge_later_wins_recursively():
    base = {"a": 1, "nest": {"x": 1, "y": 2}}
    merge_patches(base, {"a": 2, "nest": {"y": 3, "z": 4}})
    assert base == {"a": 2, "nest": {"x": 1, "y": 3, "z": 4}}


def test_merge_preserves_owned_zero_and_explicit_null():
    """The PR 9 omitempty contract survives coalescing: hostsReady: 0 and
    containerState: None are VALUES, never dropped as 'empty'."""
    base = {"readyReplicas": 1, "tpu": {"hostsReady": 2}, "containerState": {"running": {}}}
    merge_patches(base, {"readyReplicas": 0, "tpu": {"hostsReady": 0},
                         "containerState": None})
    assert base["readyReplicas"] == 0
    assert base["tpu"]["hostsReady"] == 0
    assert base["containerState"] is None
    assert "containerState" in base


def test_merge_dict_replaces_scalar_and_vice_versa():
    base = {"a": {"x": 1}, "b": 2}
    merge_patches(base, {"a": 3, "b": {"y": 4}})
    assert base == {"a": 3, "b": {"y": 4}}


# ---------------------------------------------------------------------------
# the write-rate regression: one PATCH per object per window
# ---------------------------------------------------------------------------


def test_burst_coalesces_to_leading_edge_plus_one_flush():
    client = RecordingClient()
    co = StatusCoalescer(client, window_s=0.15)
    co.start()
    try:
        # 10 adjacent patches in one sync wave
        co.patch_status(Notebook, "ns", "nb", {"readyReplicas": 1})
        for i in range(2, 10):
            co.patch_status(Notebook, "ns", "nb", {"readyReplicas": i % 2})
        co.patch_status(Notebook, "ns", "nb",
                        {"readyReplicas": 0, "containerState": None,
                         "tpu": {"hostsReady": 0}})
        # leading edge went through immediately (steady-state latency intact)
        assert len(client.calls) == 1
        assert client.calls[0][3] == {"readyReplicas": 1}
        deadline = time.monotonic() + 5
        while len(client.calls) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        # ...and exactly ONE trailing flush carrying the merged batch
        assert len(client.calls) == 2
        merged = client.calls[1][3]
        assert merged["readyReplicas"] == 0
        assert merged["containerState"] is None  # explicit null survived
        assert merged["tpu"] == {"hostsReady": 0}  # owned zero survived
        assert co.writes == 2 and co.coalesced == 8
    finally:
        co.stop()


def test_distinct_objects_do_not_coalesce_together():
    client = RecordingClient()
    co = StatusCoalescer(client, window_s=0.1)
    co.start()
    try:
        co.patch_status(Notebook, "ns", "a", {"readyReplicas": 1})
        co.patch_status(Notebook, "ns", "b", {"readyReplicas": 1})
        assert len(client.calls) == 2  # both idle: both write through
        assert {c[2] for c in client.calls} == {"a", "b"}
    finally:
        co.stop()


def test_zero_window_writes_straight_through():
    client = RecordingClient()
    co = StatusCoalescer(client, window_s=0.0)
    for i in range(5):
        co.patch_status(Notebook, "ns", "nb", {"readyReplicas": i})
    assert len(client.calls) == 5 and co.coalesced == 0


def test_stop_flushes_pending():
    client = RecordingClient()
    co = StatusCoalescer(client, window_s=30.0)  # window far beyond the test
    co.start()
    co.patch_status(Notebook, "ns", "nb", {"readyReplicas": 1})
    co.patch_status(Notebook, "ns", "nb", {"readyReplicas": 0})
    assert len(client.calls) == 1
    co.stop()  # must not wait 30s; flushes what's parked
    assert len(client.calls) == 2
    assert client.calls[1][3] == {"readyReplicas": 0}


def test_fenced_flush_dropped_not_retried():
    """Fence closed between park and flush: the ex-leader's coalesced write
    is dropped (the new leader re-mirrors), never retried or raised."""
    client = RecordingClient(error=ForbiddenError("write fenced"))
    co = StatusCoalescer(client, window_s=0.0)
    co.patch_status(Notebook, "ns", "nb", {"readyReplicas": 1})  # absorbed
    assert len(client.calls) == 1
    client2 = RecordingClient(error=NotFoundError("gone"))
    co2 = StatusCoalescer(client2, window_s=0.0)
    co2.patch_status(Notebook, "ns", "nb", {"readyReplicas": 1})  # absorbed
    assert len(client2.calls) == 1


def test_concurrent_patchers_one_flush():
    """Racing mirror threads on one object still produce bounded writes:
    leading edge + at most one flush per window."""
    client = RecordingClient()
    co = StatusCoalescer(client, window_s=0.2)
    co.start()
    try:
        threads = [
            threading.Thread(
                target=co.patch_status,
                args=(Notebook, "ns", "nb", {"readyReplicas": i % 2}),
            )
            for i in range(16)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(5)
        deadline = time.monotonic() + 5
        while co.writes < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert co.writes <= 3  # leading edge + flush (+1 timing slack)
        assert co.writes + co.coalesced == 16
    finally:
        co.stop()


# ---------------------------------------------------------------------------
# manager wiring
# ---------------------------------------------------------------------------


def test_build_manager_wires_coalescer_from_config():
    from odh_kubeflow_tpu.controllers import Config
    from odh_kubeflow_tpu.main import build_manager

    store = Store()
    config = Config(status_coalesce_window_s=0.03)
    mgr = build_manager(store, config)
    assert mgr.status_coalescer is not None
    assert mgr.status_coalescer.window_s == 0.03
    assert mgr.status_coalescer in mgr._services  # flushed at mgr.stop()
    assert mgr.status_coalescer.client is mgr.client  # fenced client: fence
    # rules apply to coalesced mirror writes exactly as to direct ones


def test_status_coalesce_window_env_knob(monkeypatch):
    from odh_kubeflow_tpu.controllers import Config

    monkeypatch.setenv("STATUS_COALESCE_WINDOW_S", "0.2")
    assert Config.from_env().status_coalesce_window_s == 0.2
    monkeypatch.setenv("STATUS_COALESCE_WINDOW_S", "-1")
    assert Config.from_env().status_coalesce_window_s == 0.0  # clamped
