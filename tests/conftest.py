"""Test fixtures.

JAX-dependent tests run on a virtual 8-device CPU mesh so multi-chip sharding
is exercised without TPU hardware (the driver separately dry-run-compiles the
multi-chip path via __graft_entry__.dryrun_multichip).
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon sitecustomize (TPU tunnel) registers its platform at interpreter
# start and overrides JAX_PLATFORMS; the config knob set post-import wins.
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:  # pragma: no cover
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Tests are written against the modern `jax.shard_map` spelling; on an older
# pinned jax the compat shim (check_vma -> check_rep) provides it.
try:
    import jax as _jax

    if not hasattr(_jax, "shard_map"):
        from odh_kubeflow_tpu import compat as _compat

        _jax.shard_map = _compat.shard_map
except ImportError:  # pragma: no cover
    pass
