"""Bench trajectory-ledger contract tests (ISSUE 15).

The ledger is judged on three things: the committed BENCH_rNN.json rounds
round-trip through the loader (including r05's null-`parsed` wrapper falling
back to its _insession report), every emitted bench report carries a
schema'd `vs_prior` block for EVERY declared headline, and the gate is green
on the committed tree while a doctored regression past tolerance fails it.
"""
from __future__ import annotations

import json
import os
import re
import shutil

import pytest

from bench import ledger

pytestmark = pytest.mark.profile

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# headline registry
# ---------------------------------------------------------------------------


def test_committed_registry_is_clean():
    assert ledger.check_headlines() == []


def test_registry_lint_rejects_malformed_headlines():
    bad = [
        ledger.Headline(name="Bad-Name", path=("detail",), direction="higher",
                        tolerance=0.1),
        ledger.Headline(name="dup", path=("a",), direction="higher",
                        tolerance=0.1),
        ledger.Headline(name="dup", path=("a",), direction="sideways",
                        tolerance=1.5),
        ledger.Headline(name="wide_no_note", path=("a",), direction="lower",
                        tolerance=0.5),
    ]
    problems = ledger.check_headlines(bad)
    assert any("snake_case" in p for p in problems)
    assert any("duplicate" in p for p in problems)
    assert any("direction" in p for p in problems)
    assert any("tolerance" in p for p in problems)
    assert any("note" in p for p in problems)


# ---------------------------------------------------------------------------
# trajectory loading (the committed tree is itself a fixture)
# ---------------------------------------------------------------------------


def test_committed_trajectory_round_trips():
    traj = ledger.load_trajectory()
    rounds = [n for n, _ in traj]
    assert rounds == sorted(rounds)
    assert set(rounds) >= {1, 2, 3, 4, 5}
    reports = dict(traj)
    # r05's wrapper has parsed=null — the loader must fall back to the
    # committed BENCH_r05_insession.json raw report
    with open(os.path.join(_ROOT, "BENCH_r05.json")) as f:
        assert json.load(f)["parsed"] is None
    r05_train = ledger._extract(
        reports[5], ("detail", "train_step", "tokens_per_s")
    )
    assert r05_train == pytest.approx(90242, abs=1)


def test_loader_skips_unrecoverable_rounds(tmp_path):
    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps({"n": 1, "parsed": {"detail": {"x": 1}}})
    )
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({"n": 2, "parsed": None}))
    (tmp_path / "BENCH_r03.json").write_text("{not json")
    traj = ledger.load_trajectory(root=str(tmp_path))
    assert [n for n, _ in traj] == [1]


def test_loader_honors_env_override(tmp_path, monkeypatch):
    (tmp_path / "BENCH_r07.json").write_text(
        json.dumps({"n": 7, "parsed": {"detail": {}}})
    )
    monkeypatch.setenv("BENCH_LEDGER_DIR", str(tmp_path))
    assert [n for n, _ in ledger.load_trajectory()] == [7]


# ---------------------------------------------------------------------------
# vs_prior / stamp
# ---------------------------------------------------------------------------


def test_vs_prior_covers_every_declared_headline():
    traj = ledger.load_trajectory()
    report = {"detail": {"train_step": {"tokens_per_s": 95000.0}}}
    block = ledger.vs_prior(report, trajectory=traj)
    assert block["schema"] == ledger.SCHEMA
    assert set(block["headlines"]) == {h.name for h in ledger.HEADLINES}
    train = block["headlines"]["train_step_tokens_per_s_v5e1"]
    assert train["prior_round"] == 5
    assert train["prior"] == pytest.approx(90242, abs=1)
    assert train["delta_frac"] == pytest.approx(0.0527, abs=0.001)
    assert train["regressed"] is False
    # no committed round carries the serving goodput headline yet: absence
    # must be visible as nulls, never silently dropped from the block
    goodput = block["headlines"]["serving_goodput_vs_static_batch"]
    assert goodput["value"] is None and goodput["prior"] is None
    assert goodput["regressed"] is False


def test_judge_directions_and_tolerance():
    higher = ledger.Headline(name="h", path=("x",), direction="higher",
                             tolerance=0.10)
    lower = ledger.Headline(name="low", path=("x",), direction="lower",
                            tolerance=0.10)
    assert ledger._judge(higher, 89.0, 100.0)["regressed"] is True
    assert ledger._judge(higher, 91.0, 100.0)["regressed"] is False
    assert ledger._judge(higher, 111.0, 100.0)["regressed"] is False
    assert ledger._judge(lower, 111.0, 100.0)["regressed"] is True
    assert ledger._judge(lower, 109.0, 100.0)["regressed"] is False
    assert ledger._judge(lower, 1.0, 0.0)["delta_frac"] is None


def test_stamp_attaches_ledger_and_where_time_went():
    snapshot = {
        "regions": {
            "serving.decode_burst": {
                "count": 3,
                "total_s": 1.0,
                "phases": {
                    "admit": {"count": 3, "total_s": 0.3, "self_s": 0.25},
                    "scan": {"count": 3, "total_s": 0.7, "self_s": 0.70},
                },
            }
        }
    }
    result = {"detail": {"train_step": {"tokens_per_s": 90000.0}}}
    ledger.stamp(result, snapshot=snapshot)
    assert result["ledger"]["schema"] == ledger.SCHEMA
    wtw = result["detail"]["where_time_went"]
    burst = wtw["serving.decode_burst"]
    assert burst["coverage"] == pytest.approx(0.95)
    assert burst["phases"]["scan"]["frac"] == pytest.approx(0.70)
    # a profiler-less run (empty snapshot) still gets the ledger block
    bare = {"detail": {}}
    ledger.stamp(bare, snapshot={"regions": {}})
    assert bare["ledger"]["schema"] == ledger.SCHEMA
    assert "where_time_went" not in bare["detail"]


# ---------------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------------


def _doctored_tree(tmp_path, mutate):
    """Copy the committed BENCH files, apply `mutate` to the LATEST round's
    raw report — the round gate_trajectory judges — wherever it lives: the
    wrapper's parsed report, or the _insession fallback when parsed is null."""
    rounds = {}
    for fname in os.listdir(_ROOT):
        if fname.startswith("BENCH_r") and fname.endswith(".json"):
            shutil.copy(os.path.join(_ROOT, fname), tmp_path / fname)
            m = re.fullmatch(r"BENCH_r(\d+)\.json", fname)
            if m:
                rounds[int(m.group(1))] = fname
    latest = rounds[max(rounds)]
    path = tmp_path / latest
    wrapper = json.loads(path.read_text())
    if wrapper.get("parsed") is not None:
        mutate(wrapper["parsed"])
        path.write_text(json.dumps(wrapper))
    else:
        path = tmp_path / latest.replace(".json", "_insession.json")
        report = json.loads(path.read_text())
        mutate(report)
        path.write_text(json.dumps(report))
    return str(tmp_path)


def test_gate_green_on_committed_tree():
    assert ledger.gate_trajectory() == []


def test_gate_fails_on_doctored_regression(tmp_path):
    # cr_to_mesh_ready is the one headline the latest round AND a prior both
    # carry (the TPU sections skip on CPU; the round-17 headlines have no
    # prior yet), so it is the only doctorable regression in this trajectory
    def regress(report):
        report["detail"]["control_plane"]["cr_to_mesh_ready_p50_s"] = 100.0

    root = _doctored_tree(tmp_path, regress)
    failures = ledger.gate_trajectory(root=root)
    assert len(failures) == 1
    assert "cr_to_mesh_ready_p50_s" in failures[0]
    assert "tolerance" in failures[0]


def test_gate_absorbs_regression_inside_tolerance(tmp_path):
    def nudge(report):
        report["detail"]["control_plane"]["cr_to_mesh_ready_p50_s"] *= 1.2

    assert ledger.gate_trajectory(root=_doctored_tree(tmp_path, nudge)) == []


def test_gate_vacuously_green_below_two_rounds(tmp_path):
    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps({"n": 1, "parsed": {"detail": {}}})
    )
    assert ledger.gate_trajectory(root=str(tmp_path)) == []


def test_gate_report_judges_fresh_file(tmp_path):
    fresh = tmp_path / "report.json"
    fresh.write_text(json.dumps(
        {"detail": {"train_step": {"tokens_per_s": 40000.0}}}
    ))
    failures = ledger.gate_report(str(fresh), root=_ROOT)
    assert len(failures) == 1 and "train_step_tokens_per_s_v5e1" in failures[0]


def test_cli_lint_and_gate_green_on_committed_tree(capsys):
    assert ledger.main(["--lint", "--gate"]) == 0
    out = capsys.readouterr().out
    assert "0 problem(s)" in out
    assert "0 regression(s)" in out


def test_cli_gate_fails_on_doctored_tree(tmp_path, monkeypatch, capsys):
    def regress(report):
        report["detail"]["control_plane"]["cr_to_mesh_ready_p50_s"] = 100.0

    monkeypatch.setenv("BENCH_LEDGER_DIR", _doctored_tree(tmp_path, regress))
    assert ledger.main(["--gate"]) == 1
    assert "cr_to_mesh_ready_p50_s" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# quick proxy (the ci/bench_gate.sh CPU lane)
# ---------------------------------------------------------------------------


def test_quick_proxy_invariants_hold():
    pytest.importorskip("jax")
    from odh_kubeflow_tpu.utils import profiler

    wtw = ledger.quick_proxy()
    burst = wtw["serving.decode_burst"]
    assert burst["coverage"] >= 0.9
    assert set(burst["phases"]) >= {"admit", "scan", "batched_drain", "emit"}
    # env + aggregates restored: quick_proxy must not leak PROFILE=1 into
    # the rest of the suite
    assert profiler.snapshot()["regions"] == {}
