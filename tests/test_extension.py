"""Extension reconciler + full-stack e2e: webhook lock -> satellites ->
lock removal -> slice up; routing/auth/netpol/CA/finalizer semantics."""
import json
import time

import pytest

from odh_kubeflow_tpu.api.apps import StatefulSet
from odh_kubeflow_tpu.api.core import ConfigMap, Pod, Secret, Service, ServiceAccount, Container
from odh_kubeflow_tpu.api.gateway import HTTPRoute, ReferenceGrant
from odh_kubeflow_tpu.api.networking import NetworkPolicy
from odh_kubeflow_tpu.api.rbac import ClusterRoleBinding, Role, RoleBinding
from odh_kubeflow_tpu.api.notebook import Notebook, TPUSpec
from odh_kubeflow_tpu.apimachinery import NotFoundError
from odh_kubeflow_tpu.cluster import SimCluster
from odh_kubeflow_tpu.cluster.client import retry_on_conflict
from odh_kubeflow_tpu.controllers import Config, constants as C
from odh_kubeflow_tpu.controllers.extension import (
    REFERENCE_GRANT_NAME,
    RUNTIME_IMAGES_CONFIGMAP,
    auth_binding_name,
    route_name,
)
from odh_kubeflow_tpu.main import build_manager
from odh_kubeflow_tpu.probe import sim_agent_behavior

CTRL_NS = "tpu-notebooks-system"


@pytest.fixture()
def env():
    cluster = SimCluster().start()
    cluster.add_cpu_pool("cpu", nodes=2)
    cluster.add_tpu_pool("v5e", "v5e", "2x2")
    agents = {}
    cluster.add_pod_behavior(sim_agent_behavior(agents))
    config = Config(controller_namespace=CTRL_NS, set_pipeline_rbac=True,
                    set_pipeline_secret=True, readiness_probe_period_s=0.3)
    mgr = build_manager(cluster.store, config, http_get=cluster.http_get)
    mgr.start()
    yield cluster, mgr, config
    mgr.stop()
    cluster.stop()


def mk_nb(name, ns="user", annotations=None, tpu=None):
    nb = Notebook()
    nb.metadata.name = name
    nb.metadata.namespace = ns
    nb.metadata.annotations = dict(annotations or {})
    nb.spec.template.spec.containers = [Container(name=name, image="jax:1")]
    if tpu:
        nb.spec.tpu = tpu
    return nb


def wait_for(fn, timeout=10, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            out = fn()
            if out:
                return out
        except NotFoundError:
            pass
        time.sleep(0.05)
    raise AssertionError(f"timeout: {msg}")


def test_full_lifecycle_lock_handshake(env):
    """The reference's signature flow (SURVEY §3.2): webhook locks at CREATE,
    STS starts at 0, extension builds satellites and removes the lock, STS
    scales up, pods run."""
    cluster, mgr, config = env
    created = cluster.client.create(mk_nb("wb", tpu=TPUSpec(accelerator="v5e", topology="2x2")))
    # webhook injected the lock at admission
    assert created.metadata.annotations[C.STOP_ANNOTATION] == C.RECONCILIATION_LOCK_VALUE

    # extension removes the lock once satellites exist -> slice comes up
    nb = wait_for(
        lambda: (
            lambda n: n if n.status.tpu and n.status.tpu.mesh_ready else None
        )(cluster.client.get(Notebook, "user", "wb")),
        msg="mesh ready after lock removal", timeout=15,
    )
    assert C.STOP_ANNOTATION not in nb.metadata.annotations
    assert set(nb.metadata.finalizers) >= {
        C.ROUTE_FINALIZER, C.REFERENCE_GRANT_FINALIZER, C.AUTH_BINDING_FINALIZER
    }

    # satellites
    route = cluster.client.get(HTTPRoute, CTRL_NS, route_name(nb))
    assert route.spec.rules[0].matches[0].path.value == "/notebook/user/wb"
    backend = route.spec.rules[0].backend_refs[0]
    assert backend.name == "wb" and backend.namespace == "user" and backend.port == 80
    assert cluster.client.get(ReferenceGrant, "user", REFERENCE_GRANT_NAME)
    nps = cluster.client.list(NetworkPolicy, namespace="user")
    assert any(np.metadata.name == "wb-ctrl-np" for np in nps)


def test_user_stop_annotation_not_removed(env):
    """The lock remover must never unstop a USER-stopped notebook."""
    cluster, mgr, config = env
    cluster.client.create(mk_nb("stopped"))
    wait_for(
        lambda: C.STOP_ANNOTATION
        not in cluster.client.get(Notebook, "user", "stopped").metadata.annotations,
        msg="lock removed",
    )
    # user stops it explicitly (timestamp value, not the lock value)
    cluster.client.patch(
        Notebook, "user", "stopped",
        {"metadata": {"annotations": {C.STOP_ANNOTATION: "2026-07-29T10:00:00Z"}}},
    )
    time.sleep(1.0)
    nb = cluster.client.get(Notebook, "user", "stopped")
    assert nb.metadata.annotations[C.STOP_ANNOTATION] == "2026-07-29T10:00:00Z"


def test_auth_mode_objects_and_route_retarget(env):
    cluster, mgr, config = env
    cluster.client.create(mk_nb("secure", annotations={C.INJECT_AUTH_ANNOTATION: "true"}))
    wait_for(
        lambda: cluster.client.get(Service, "user", "secure-kube-rbac-proxy"),
        msg="auth service",
    )
    assert cluster.client.get(ServiceAccount, "user", "secure")
    sar_cm = cluster.client.get(ConfigMap, "user", "secure-kube-rbac-proxy-config")
    sar = json.loads(sar_cm.data["config-file.yaml"])
    attrs = sar["authorization"]["resourceAttributes"]
    assert attrs["name"] == "secure" and attrs["verb"] == "get"
    nb = cluster.client.get(Notebook, "user", "secure")
    crb = cluster.client.get(ClusterRoleBinding, "", auth_binding_name(nb))
    assert crb.role_ref.name == "system:auth-delegator"
    # route targets the proxy
    route = wait_for(
        lambda: cluster.client.get(HTTPRoute, CTRL_NS, route_name(nb)), msg="route"
    )
    backend = route.spec.rules[0].backend_refs[0]
    assert backend.name == "secure-kube-rbac-proxy" and backend.port == 8443
    # sidecar injected by webhook
    sts = cluster.client.get(StatefulSet, "user", "secure")
    assert any(c.name == "kube-rbac-proxy" for c in sts.spec.template.spec.containers)
    # auth network policy exists
    wait_for(
        lambda: cluster.client.get(NetworkPolicy, "user", "secure-kube-rbac-proxy-np"),
        msg="auth netpol",
    )

    # switching auth OFF retargets the route back (notebook is running ->
    # update-blocking applies to podspec, but annotations flow)
    cluster.client.patch(
        Notebook, "user", "secure",
        {"metadata": {"annotations": {C.INJECT_AUTH_ANNOTATION: None}}},
    )
    wait_for(
        lambda: cluster.client.get(HTTPRoute, CTRL_NS, route_name(nb))
        .spec.rules[0]
        .backend_refs[0]
        .port
        == 80,
        msg="route retargeted",
    )


def test_deletion_cleans_cross_namespace_objects(env):
    cluster, mgr, config = env
    cluster.client.create(mk_nb("temp", annotations={C.INJECT_AUTH_ANNOTATION: "true"}))
    nb = wait_for(lambda: cluster.client.get(Notebook, "user", "temp"), msg="nb")
    wait_for(lambda: cluster.client.get(HTTPRoute, CTRL_NS, route_name(nb)), msg="route")
    crb_name = auth_binding_name(nb)
    wait_for(lambda: cluster.client.get(ClusterRoleBinding, "", crb_name), msg="crb")

    cluster.client.delete(Notebook, "user", "temp")
    wait_for(
        lambda: _not_found(lambda: cluster.client.get(Notebook, "user", "temp")),
        msg="notebook finalized away",
    )
    assert _not_found(lambda: cluster.client.get(HTTPRoute, CTRL_NS, route_name(nb)))
    assert _not_found(lambda: cluster.client.get(ClusterRoleBinding, "", crb_name))
    assert _not_found(
        lambda: cluster.client.get(ReferenceGrant, "user", REFERENCE_GRANT_NAME)
    )


def test_reference_grant_shared_until_last_notebook(env):
    cluster, mgr, config = env
    cluster.client.create(mk_nb("a1"))
    cluster.client.create(mk_nb("a2"))
    wait_for(
        lambda: cluster.client.get(ReferenceGrant, "user", REFERENCE_GRANT_NAME),
        msg="grant",
    )
    cluster.client.delete(Notebook, "user", "a1")
    wait_for(
        lambda: _not_found(lambda: cluster.client.get(Notebook, "user", "a1")),
        msg="a1 gone",
    )
    # grant survives: a2 still needs it
    assert cluster.client.get(ReferenceGrant, "user", REFERENCE_GRANT_NAME)
    cluster.client.delete(Notebook, "user", "a2")
    wait_for(
        lambda: _not_found(
            lambda: cluster.client.get(ReferenceGrant, "user", REFERENCE_GRANT_NAME)
        ),
        msg="grant removed with last notebook",
    )


def test_ca_bundle_assembled_and_mounted(env):
    cluster, mgr, config = env
    src = ConfigMap()
    src.metadata.name = "odh-trusted-ca-bundle"
    src.metadata.namespace = CTRL_NS
    src.data = {"ca-bundle.crt": "-----BEGIN CERTIFICATE-----\nAAA\n-----END CERTIFICATE-----"}
    cluster.client.create(src)
    cluster.client.create(mk_nb("certd"))
    bundle = wait_for(
        lambda: cluster.client.get(ConfigMap, "user", "workbench-trusted-ca-bundle"),
        msg="bundle assembled",
    )
    assert "BEGIN CERTIFICATE" in bundle.data["ca-bundle.crt"]
    # webhook mounts it on the next podspec-bearing admission; force one by
    # stopping/starting (stopped notebooks take updates freely)
    cluster.client.patch(
        Notebook, "user", "certd",
        {"metadata": {"annotations": {C.STOP_ANNOTATION: "x"}}},
    )
    def bump_image():
        nb = cluster.client.get(Notebook, "user", "certd")
        nb.spec.template.spec.containers[0].image = "jax:2"
        return cluster.client.update(nb)

    retry_on_conflict(bump_image)  # races controller status writes
    nb = cluster.client.get(Notebook, "user", "certd")
    assert nb.spec.template.spec.volume("trusted-ca") is not None


def test_runtime_images_synced(env):
    cluster, mgr, config = env
    src = ConfigMap()
    src.metadata.name = "runtime-catalog"
    src.metadata.namespace = CTRL_NS
    src.metadata.labels = {C.RUNTIME_IMAGE_LABEL: "true"}
    src.data = {
        "JAX 0.9 on TPU": json.dumps(
            {"display_name": "JAX 0.9 on TPU", "metadata": {"image_name": "gcr.io/jax:0.9"}}
        )
    }
    cluster.client.create(src)
    cluster.client.create(mk_nb("rt"))
    cm = wait_for(
        lambda: cluster.client.get(ConfigMap, "user", RUNTIME_IMAGES_CONFIGMAP),
        msg="runtime images synced",
    )
    assert "jax_0.9_on_tpu.json" in cm.data


def test_pipeline_rbac_and_elyra_secret(env):
    cluster, mgr, config = env
    role = Role()
    role.metadata.name = "ds-pipeline-user-access-dspa"
    role.metadata.namespace = "user"
    cluster.client.create(role)
    src = Secret()
    src.metadata.name = "pipeline-server-config"
    src.metadata.namespace = CTRL_NS
    src.string_data = {
        "api_endpoint": "https://dspa.svc:8443",
        "cos_endpoint": "https://minio.svc",
        "cos_bucket": "pipelines",
        "cos_username": "minio",
        "cos_password": "secret",
    }
    cluster.client.create(src)
    cluster.client.create(mk_nb("pl"))
    rb = wait_for(
        lambda: cluster.client.get(RoleBinding, "user", "elyra-pipelines-pl"),
        msg="pipeline rolebinding",
    )
    assert rb.role_ref.name == "ds-pipeline-user-access-dspa"
    secret = wait_for(
        lambda: cluster.client.get(Secret, "user", "ds-pipeline-config"),
        msg="elyra secret",
    )
    cfg = json.loads(secret.string_data["odh_dsp.json"])
    assert cfg["metadata"]["cos_bucket"] == "pipelines"
    assert cfg["metadata"]["api_endpoint"] == "https://dspa.svc:8443"


def _not_found(fn):
    try:
        fn()
        return False
    except NotFoundError:
        return True


def test_auth_mode_gateway_cannot_reach_notebook_port(env):
    """Auth notebooks: the gateway namespace may only reach :8443 — admitting
    it to :8888 would let any route on the shared Gateway bypass the
    SubjectAccessReview."""
    cluster, mgr, config = env
    from odh_kubeflow_tpu.api.networking import NetworkPolicy
    from odh_kubeflow_tpu.controllers.constants import NOTEBOOK_PORT

    cluster.client.create(
        mk_nb("authed", annotations={C.INJECT_AUTH_ANNOTATION: "true"})
    )
    np = wait_for(
        lambda: cluster.client.get(NetworkPolicy, "user", "authed-ctrl-np"),
        msg="ctrl network policy",
    )
    nb_rule = next(
        r for r in np.spec.ingress if r.ports[0].port == NOTEBOOK_PORT
    )
    peers = [
        p.namespace_selector.match_labels.get("kubernetes.io/metadata.name")
        for p in nb_rule.from_
        if p.namespace_selector
    ]
    assert config.gateway_namespace not in peers
    assert CTRL_NS in peers


def test_runtime_images_pruned_when_sources_removed(env):
    """Removing the last runtime-image source must prune the per-ns catalog."""
    cluster, mgr, config = env
    src = ConfigMap()
    src.metadata.name = "runtime-jax"
    src.metadata.namespace = CTRL_NS
    src.metadata.labels = {C.RUNTIME_IMAGE_LABEL: "true"}
    src.data = {"JAX 2026a": '{"display_name": "JAX 2026a", "image_name": "x"}'}
    cluster.client.create(src)
    cluster.client.create(mk_nb("rt"))
    wait_for(
        lambda: cluster.client.get(ConfigMap, "user", "pipeline-runtime-images"),
        msg="runtime images synced",
    )
    cluster.client.delete(ConfigMap, CTRL_NS, "runtime-jax")
    # touch the notebook to trigger a reconcile
    cluster.client.patch(
        Notebook, "user", "rt", {"metadata": {"annotations": {"poke": "1"}}}
    )

    def pruned():
        try:
            cluster.client.get(ConfigMap, "user", "pipeline-runtime-images")
            return False
        except NotFoundError:
            return True

    wait_for(pruned, msg="stale catalog pruned")
