"""L8 parallel: mesh planning, logical-axis sharding rules, env bring-up."""
import os

import jax
import pytest
from jax.sharding import PartitionSpec

from odh_kubeflow_tpu.parallel import (
    MeshPlan,
    initialize_from_env,
    shard_batch,
    slice_mesh_axes,
)
from odh_kubeflow_tpu.parallel.mesh import logical_to_spec
from odh_kubeflow_tpu.tpu import plan_slice


def test_auto_plan_factors_exactly():
    for n in (1, 2, 4, 8, 16, 32):
        plan = MeshPlan.auto(n, want_sp=2, want_tp=2)
        assert plan.n_devices == n
    # non-dividing wants are capped, never crash
    assert MeshPlan.auto(6, want_sp=4, want_tp=4).n_devices == 6
    assert MeshPlan.auto(1, want_sp=8, want_tp=8) == MeshPlan()


def test_mesh_build_and_axis_order():
    mesh = MeshPlan(fsdp=2, tp=2, sp=2).build()
    assert mesh.axis_names == ("dp", "fsdp", "tp", "sp")
    assert mesh.devices.shape == (1, 2, 2, 2)
    with pytest.raises(ValueError):
        MeshPlan(fsdp=4).build(jax.devices()[:3])


def test_logical_to_spec_drops_dead_axes():
    mesh = MeshPlan(fsdp=2, tp=2, sp=2).build()
    assert logical_to_spec(("batch", "seq"), mesh) == PartitionSpec("fsdp", "sp")
    assert logical_to_spec(("embed", "heads", "head_dim"), mesh) == PartitionSpec(
        "fsdp", "tp"
    )
    # all-dp mesh of size 1 on those axes -> fully replicated
    mesh1 = MeshPlan(dp=8).build()
    assert logical_to_spec(("embed", "heads"), mesh1) == PartitionSpec()
    assert logical_to_spec(("batch", "seq"), mesh1) == PartitionSpec("dp")
    with pytest.raises(KeyError):
        logical_to_spec(("nonsense",), mesh)


def test_shard_batch_places_on_mesh():
    import jax.numpy as jnp

    mesh = MeshPlan(fsdp=4, sp=2).build()
    batch = shard_batch(mesh, {"tokens": jnp.ones((8, 16), jnp.int32)})
    sharding = batch["tokens"].sharding
    assert sharding.spec == PartitionSpec("fsdp", "sp")


def test_initialize_from_env_single_host_noop(monkeypatch):
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert initialize_from_env() == (0, 1)


def test_initialize_from_env_missing_coordinator(monkeypatch):
    monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
    monkeypatch.setenv("JAX_PROCESS_ID", "2")
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "")
    with pytest.raises(RuntimeError, match="webhook env injection"):
        initialize_from_env()


def test_slice_mesh_axes_defaults_tp_to_host_chips():
    shape = plan_slice("v5p", topology="2x2x4")  # 16 chips, 4 hosts x 4
    plan = slice_mesh_axes(shape)
    assert plan.n_devices == 16
    assert plan.tp == 4  # tp collectives stay on one host's chips
    long_ctx = slice_mesh_axes(shape, want_sp=4)
    assert long_ctx.sp == 4 and long_ctx.n_devices == 16
