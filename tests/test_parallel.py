"""L8 parallel: mesh planning, logical-axis sharding rules, env bring-up."""
import os

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec

from odh_kubeflow_tpu.parallel import (
    MeshPlan,
    initialize_from_env,
    shard_batch,
    slice_mesh_axes,
)
from odh_kubeflow_tpu.parallel.mesh import logical_to_spec
from odh_kubeflow_tpu.tpu import plan_slice


def test_auto_plan_factors_exactly():
    for n in (1, 2, 4, 8, 16, 32):
        plan = MeshPlan.auto(n, want_sp=2, want_tp=2)
        assert plan.n_devices == n
    # non-dividing wants are capped, never crash
    assert MeshPlan.auto(6, want_sp=4, want_tp=4).n_devices == 6
    assert MeshPlan.auto(1, want_sp=8, want_tp=8) == MeshPlan()


def test_mesh_build_and_axis_order():
    mesh = MeshPlan(fsdp=2, tp=2, sp=2).build()
    assert mesh.axis_names == ("dp", "fsdp", "pp", "ep", "tp", "sp")
    assert mesh.devices.shape == (1, 2, 1, 1, 2, 2)
    with pytest.raises(ValueError):
        MeshPlan(fsdp=4).build(jax.devices()[:3])


def test_logical_to_spec_drops_dead_axes():
    mesh = MeshPlan(fsdp=2, tp=2, sp=2).build()
    assert logical_to_spec(("batch", "seq"), mesh) == PartitionSpec("fsdp", "sp")
    assert logical_to_spec(("embed", "heads", "head_dim"), mesh) == PartitionSpec(
        "fsdp", "tp"
    )
    # all-dp mesh of size 1 on those axes -> fully replicated
    mesh1 = MeshPlan(dp=8).build()
    assert logical_to_spec(("embed", "heads"), mesh1) == PartitionSpec()
    assert logical_to_spec(("batch", "seq"), mesh1) == PartitionSpec("dp")
    with pytest.raises(KeyError):
        logical_to_spec(("nonsense",), mesh)


def test_shard_batch_places_on_mesh():
    import jax.numpy as jnp

    mesh = MeshPlan(fsdp=4, sp=2).build()
    batch = shard_batch(mesh, {"tokens": jnp.ones((8, 16), jnp.int32)})
    sharding = batch["tokens"].sharding
    assert sharding.spec == PartitionSpec("fsdp", "sp")


def test_initialize_from_env_single_host_noop(monkeypatch):
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert initialize_from_env() == (0, 1)


def test_initialize_from_env_missing_coordinator(monkeypatch):
    monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
    monkeypatch.setenv("JAX_PROCESS_ID", "2")
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "")
    with pytest.raises(RuntimeError, match="webhook env injection"):
        initialize_from_env()


def test_slice_mesh_axes_defaults_tp_to_host_chips():
    shape = plan_slice("v5p", topology="2x2x4")  # 16 chips, 4 hosts x 4
    plan = slice_mesh_axes(shape)
    assert plan.n_devices == 16
    assert plan.tp == 4  # tp collectives stay on one host's chips
    long_ctx = slice_mesh_axes(shape, want_sp=4)
    assert long_ctx.sp == 4 and long_ctx.n_devices == 16


# ---- pipeline parallelism (pp axis) ----


def test_pipeline_apply_matches_sequential():
    """pp=4 pipeline over microbatches == running the stages sequentially."""
    import numpy as np

    from odh_kubeflow_tpu.parallel import MeshPlan, pipeline_apply, stack_stages

    plan = MeshPlan.auto(8, want_pp=4, want_tp=2)
    assert plan.pp == 4
    mesh = plan.build(jax.devices()[:8])

    L, d = 8, 16
    key = jax.random.PRNGKey(0)
    w = jax.random.normal(key, (L, d, d)) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(1), (8, d))

    def stage_fn(stage_w, h):
        def body(carry, wi):
            return jnp.tanh(carry @ wi), None

        h, _ = jax.lax.scan(body, h, stage_w)
        return h

    stages = stack_stages(w, 4)
    assert stages.shape == (4, 2, d, d)
    y_pipe = jax.jit(
        lambda s, x: pipeline_apply(stage_fn, s, x, mesh, n_micro=4)
    )(stages, x)

    y_seq = x
    for i in range(L):
        y_seq = jnp.tanh(y_seq @ w[i])
    assert np.allclose(np.asarray(y_pipe), np.asarray(y_seq), atol=1e-5)


def test_pipeline_gradients_match_sequential():
    """Backprop through ppermute hops equals the sequential gradient."""
    import numpy as np

    from odh_kubeflow_tpu.parallel import MeshPlan, pipeline_apply, stack_stages

    mesh = MeshPlan.auto(8, want_pp=2, want_tp=4).build(jax.devices()[:8])
    L, d = 4, 8
    w = jax.random.normal(jax.random.PRNGKey(0), (L, d, d)) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(1), (4, d))

    def stage_fn(stage_w, h):
        def body(carry, wi):
            return jnp.tanh(carry @ wi), None

        h, _ = jax.lax.scan(body, h, stage_w)
        return h

    def loss_pipe(w):
        y = pipeline_apply(stage_fn, stack_stages(w, 2), x, mesh, n_micro=2)
        return jnp.sum(y**2)

    def loss_seq(w):
        y = x
        for i in range(L):
            y = jnp.tanh(y @ w[i])
        return jnp.sum(y**2)

    g_pipe = jax.jit(jax.grad(loss_pipe))(w)
    g_seq = jax.grad(loss_seq)(w)
    assert np.allclose(np.asarray(g_pipe), np.asarray(g_seq), atol=1e-5)


@pytest.mark.slow  # compile-heavy CPU-mesh parity (minutes): run via -m slow
def test_pp_transformer_train_step():
    """Flagship model trains under pp=2 with sharded stage params; loss
    matches the non-pipelined model on identical inputs."""
    import numpy as np
    from jax.sharding import NamedSharding

    from odh_kubeflow_tpu.models import (
        TransformerConfig,
        init_params,
        loss_fn,
        make_pp_train_step,
        pp_param_specs,
    )
    from odh_kubeflow_tpu.parallel import MeshPlan, shard_batch
    from odh_kubeflow_tpu.parallel.pipeline import stack_stages

    plan = MeshPlan.auto(8, want_pp=2, want_tp=2)
    assert plan.pp == 2
    mesh = plan.build(jax.devices()[:8])
    cfg = TransformerConfig(
        vocab=64,
        d_model=32,
        n_layers=4,
        n_heads=2,
        d_ff=64,
        dtype=jnp.float32,
        use_flash=False,
        remat=False,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    ref_loss = loss_fn(params, {"tokens": jnp.ones((4, 16), jnp.int32)}, cfg)

    from odh_kubeflow_tpu.models.transformer import to_pp_params

    pp_params = to_pp_params(params, 2, cfg, mesh)
    specs = pp_param_specs(cfg, mesh, 2)
    pp_params = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), pp_params, specs
    )
    step, opt = make_pp_train_step(cfg, mesh, n_micro=2)
    opt_state = opt.init(pp_params)
    batch = shard_batch(mesh, {"tokens": jnp.ones((4, 16), jnp.int32)})
    new_params, opt_state, loss = jax.jit(step)(pp_params, opt_state, batch)
    jax.block_until_ready(loss)
    assert np.allclose(float(loss), float(ref_loss), atol=1e-4)


@pytest.mark.slow  # compile-heavy CPU-mesh parity (minutes): run via -m slow
def test_pp_tp_manual_stage_parallelism():
    """VERDICT r4 #2: pp composes with tp — stage matmuls run manual
    Megatron-style tensor parallelism (wqkv/wi column-parallel, wo/wo_mlp
    row-parallel + psum) and stage storage shards over tp AND fsdp (ZeRO,
    gathered once per step). Loss AND gradients match the non-pipelined
    model; per-device stage-param bytes drop by tp*fsdp."""
    import numpy as np
    from jax.sharding import NamedSharding

    from odh_kubeflow_tpu.models import (
        TransformerConfig,
        init_params,
        loss_fn,
        pp_param_specs,
    )
    from odh_kubeflow_tpu.models.transformer import pp_loss_fn, to_pp_params
    from odh_kubeflow_tpu.parallel import MeshPlan, shard_batch

    plan = MeshPlan(fsdp=2, pp=2, tp=2)
    mesh = plan.build(jax.devices()[:8])
    cfg = TransformerConfig(
        vocab=64,
        d_model=32,
        n_layers=4,
        n_heads=4,
        n_kv_heads=2,  # GQA: contiguous-block tp sharding preserves groups
        d_ff=64,
        dtype=jnp.float32,
        use_flash=False,
        remat=False,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab)
    ref_loss, ref_grads = jax.value_and_grad(loss_fn)(
        params, {"tokens": tokens}, cfg
    )

    pp_params = to_pp_params(params, 2, cfg, mesh)
    specs = pp_param_specs(cfg, mesh, 2)
    # storage: wqkv sharded pp x fsdp(embed) x tp(fused heads)
    assert specs["layers"]["wqkv"] == jax.sharding.PartitionSpec(
        "pp", None, "fsdp", "tp", None
    )
    pp_params = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), pp_params, specs
    )
    wq = pp_params["layers"]["wqkv"]
    # per-device bytes: 1/(pp*fsdp*tp) of the full stack = 1/8
    assert wq.addressable_shards[0].data.size * 8 == wq.size

    batch = shard_batch(mesh, {"tokens": tokens})
    loss, grads = jax.jit(jax.value_and_grad(
        lambda p: pp_loss_fn(p, batch, cfg, mesh, n_micro=2)
    ))(pp_params)
    jax.block_until_ready(loss)
    assert np.allclose(float(loss), float(ref_loss), atol=1e-5)

    # gradient parity: un-stack the pipeline grads back to (L, ...) and
    # un-permute wqkv's fused axis, then compare leaf by leaf
    from odh_kubeflow_tpu.models.transformer import _interleave_wqkv

    ref_l = ref_grads["layers"]
    got_l = grads["layers"]
    # invert the interleave on the REFERENCE side (permutation is involutive
    # only for tp=2 when h==2kv; invert explicitly by permuting ref the same
    # way instead)
    ref_wqkv = _interleave_wqkv(ref_l["wqkv"], cfg.n_heads, cfg.kv_heads, 2)
    for name in ref_l:
        want = ref_wqkv if name == "wqkv" else ref_l[name]
        got = np.asarray(got_l[name]).reshape(want.shape)
        np.testing.assert_allclose(
            got, np.asarray(want), atol=5e-5, rtol=1e-4, err_msg=name
        )
    for name in ("embed", "unembed", "final_norm"):
        np.testing.assert_allclose(
            np.asarray(grads[name]), np.asarray(ref_grads[name]),
            atol=5e-5, rtol=1e-4, err_msg=name,
        )


@pytest.mark.slow  # compile-heavy CPU-mesh parity (minutes): run via -m slow
def test_pp_1f1b_matches_gpipe_and_sequential():
    """VERDICT r4 #8: the 1F1B schedule produces the same loss and gradients
    as GPipe (and the non-pipelined model) to float tolerance, across
    pp x tp x fsdp with ZeRO stage storage; its activation-memory profile is
    O(stages), exercised here with n_micro=4 > W."""
    import numpy as np
    from jax.sharding import NamedSharding

    from odh_kubeflow_tpu.models import (
        TransformerConfig,
        init_params,
        loss_fn,
        pp_param_specs,
    )
    from odh_kubeflow_tpu.models.transformer import (
        pp_1f1b_value_and_grad,
        pp_loss_fn,
        to_pp_params,
    )
    from odh_kubeflow_tpu.parallel import MeshPlan, shard_batch

    plan = MeshPlan(fsdp=2, pp=2, tp=2)
    mesh = plan.build(jax.devices()[:8])
    cfg = TransformerConfig(
        vocab=64,
        d_model=32,
        n_layers=4,
        n_heads=4,
        n_kv_heads=2,
        d_ff=64,
        dtype=jnp.float32,
        use_flash=False,
        remat=False,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab)
    ref_loss, ref_grads = jax.value_and_grad(loss_fn)(
        params, {"tokens": tokens}, cfg
    )

    pp_params = to_pp_params(params, 2, cfg, mesh)
    specs = pp_param_specs(cfg, mesh, 2)
    pp_params = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), pp_params, specs
    )
    batch = shard_batch(mesh, {"tokens": tokens})

    g_loss, g_grads = jax.jit(jax.value_and_grad(
        lambda p: pp_loss_fn(p, batch, cfg, mesh, n_micro=4)
    ))(pp_params)
    f_loss, f_grads = jax.jit(
        lambda p, b: pp_1f1b_value_and_grad(p, b, cfg, mesh, n_micro=4)
    )(pp_params, batch)
    jax.block_until_ready(f_loss)

    assert np.allclose(float(f_loss), float(g_loss), atol=1e-6)
    assert np.allclose(float(f_loss), float(ref_loss), atol=1e-5)
    flat_g, _ = jax.tree_util.tree_flatten_with_path(g_grads)
    flat_f, _ = jax.tree_util.tree_flatten_with_path(f_grads)
    for (path_g, a), (path_f, b) in zip(flat_g, flat_f):
        assert path_g == path_f
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=1e-6, rtol=1e-5,
            err_msg=jax.tree_util.keystr(path_g),
        )


def test_interleaved_pipeline_matches_sequential():
    """Virtual-stage (interleaved) schedule: pp=2 x v=2 chunks over 8 layers
    equals running the stack sequentially, values AND gradients — the bubble
    shrinks by v while the single ppermute ring stays unchanged."""
    import numpy as np

    from odh_kubeflow_tpu.parallel import MeshPlan, pipeline_apply, stack_stages

    mesh = MeshPlan.auto(8, want_pp=2, want_tp=4).build(jax.devices()[:8])
    L, d = 8, 16
    w = jax.random.normal(jax.random.PRNGKey(0), (L, d, d)) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(1), (8, d))

    def stage_fn(stage_w, h):
        def body(carry, wi):
            return jnp.tanh(carry @ wi), None

        h, _ = jax.lax.scan(body, h, stage_w)
        return h

    def loss_pipe(w):
        y = pipeline_apply(
            stage_fn, stack_stages(w, 2, n_chunks=2), x, mesh,
            n_micro=4, n_chunks=2,
        )
        return jnp.sum(y**2), y

    def loss_seq(w):
        y = x
        for i in range(L):
            y = jnp.tanh(y @ w[i])
        return jnp.sum(y**2), y

    (_, y_pipe), g_pipe = jax.jit(
        jax.value_and_grad(loss_pipe, has_aux=True)
    )(w)
    (_, y_seq), g_seq = jax.value_and_grad(loss_seq, has_aux=True)(w)
    assert np.allclose(np.asarray(y_pipe), np.asarray(y_seq), atol=1e-5)
    assert np.allclose(np.asarray(g_pipe), np.asarray(g_seq), atol=1e-5)

    # ragged n_micro rejected (schedule injects in groups of S)
    import pytest

    with pytest.raises(ValueError, match="divisible"):
        pipeline_apply(
            stage_fn, stack_stages(w, 2, n_chunks=2), x, mesh,
            n_micro=1, n_chunks=2,
        )


@pytest.mark.slow  # compile-heavy CPU-mesh parity (minutes): run via -m slow
def test_interleaved_pp_transformer_parity():
    """Interleaved virtual stages on the flagship model: pp=2 x v=2 over 8
    layers, composed with manual tp + ZeRO stage storage — loss and
    gradients match the non-pipelined model."""
    import numpy as np
    from jax.sharding import NamedSharding

    from odh_kubeflow_tpu.models import (
        TransformerConfig,
        init_params,
        loss_fn,
        pp_param_specs,
    )
    from odh_kubeflow_tpu.models.transformer import pp_loss_fn, to_pp_params
    from odh_kubeflow_tpu.parallel import MeshPlan, shard_batch

    plan = MeshPlan(fsdp=2, pp=2, tp=2)
    mesh = plan.build(jax.devices()[:8])
    cfg = TransformerConfig(
        vocab=64,
        d_model=32,
        n_layers=8,
        n_heads=4,
        n_kv_heads=2,
        d_ff=64,
        dtype=jnp.float32,
        use_flash=False,
        remat=False,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab)
    ref_loss, ref_grads = jax.value_and_grad(loss_fn)(
        params, {"tokens": tokens}, cfg
    )

    pp_params = to_pp_params(params, 2, cfg, mesh, n_chunks=2)
    specs = pp_param_specs(cfg, mesh, 2, n_chunks=2)
    assert specs["layers"]["wqkv"] == jax.sharding.PartitionSpec(
        "pp", None, None, "fsdp", "tp", None
    )
    pp_params = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), pp_params, specs
    )
    batch = shard_batch(mesh, {"tokens": tokens})
    loss, grads = jax.jit(jax.value_and_grad(
        lambda p: pp_loss_fn(p, batch, cfg, mesh, n_micro=2, n_chunks=2)
    ))(pp_params)
    jax.block_until_ready(loss)
    assert np.allclose(float(loss), float(ref_loss), atol=1e-5)

    # gradient parity: the interleaved chunk layout maps ref layer group
    # g = c*S + r to pp grads [r, c]; un-permute before comparing
    from odh_kubeflow_tpu.models.transformer import _interleave_wqkv

    S, v = 2, 2
    lg = cfg.n_layers // (S * v)
    ref_l = dict(ref_grads["layers"])
    ref_l["wqkv"] = _interleave_wqkv(ref_l["wqkv"], cfg.n_heads, cfg.kv_heads, 2)
    for name, want in ref_l.items():
        got = np.asarray(grads["layers"][name])  # (S, v, lg, ...)
        want_groups = np.asarray(want).reshape(S * v, lg, *want.shape[1:])
        for r in range(S):
            for c in range(v):
                np.testing.assert_allclose(
                    got[r, c], want_groups[c * S + r], atol=5e-5, rtol=1e-4,
                    err_msg=f"{name}[{r},{c}]",
                )


def test_interleaved_1f1b_schedule_invariants():
    """Megatron-order interleaved 1F1B tables: coverage, dependencies and
    buffer bounds hold across shapes; fill+drain lands at
    (v-1)*S + 2*(S-1) paired steps (the bubble the schedule exists to
    shrink: less than plain 1F1B's v*2*(S-1) chunk-equivalents for S > 2),
    and buffer widths are O(S*v), independent of n_micro."""
    from odh_kubeflow_tpu.parallel.interleaved_1f1b import (
        build_schedule,
        validate_schedule,
    )

    for (S, v, m) in [(2, 2, 4), (4, 2, 8), (2, 4, 8), (4, 4, 16), (8, 2, 16)]:
        s = build_schedule(S, v, m)
        validate_schedule(s)
        fill_drain = s.T - m * v
        assert fill_drain == (v - 1) * S + 2 * (S - 1), (S, v, m, s.T)
        if S > 2:
            # wall in chunk-pair units beats plain 1F1B's v*(m + 2(S-1))
            assert s.T < v * (m + 2 * (S - 1)), (S, v, m, s.T)
        assert s.in_width <= (v + 1) * S + 2, (S, v, s.in_width)
        assert s.recvf_width <= 3 and s.recvb_width <= 6

    # memory boundedness: quadrupling n_micro must not grow any buffer
    a = build_schedule(4, 2, 8)
    b = build_schedule(4, 2, 32)
    assert (a.in_width, a.recvf_width, a.recvb_width, a.dyh_width) == (
        b.in_width, b.recvf_width, b.recvb_width, b.dyh_width
    )

    import pytest

    with pytest.raises(ValueError, match="divisible"):
        build_schedule(4, 2, 6)


@pytest.mark.slow  # compile-heavy CPU-mesh parity (minutes): run via -m slow
def test_interleaved_1f1b_transformer_parity():
    """VERDICT r4 #4 — Megatron's interleaved 1F1B on the flagship model:
    pp=2 x v=2 over 8 layers with manual tp + ZeRO stage storage; loss and
    gradients match the interleaved-GPipe pipeline (autodiff) and the
    non-pipelined model."""
    import numpy as np
    from jax.sharding import NamedSharding

    from odh_kubeflow_tpu.models import (
        TransformerConfig,
        init_params,
        loss_fn,
        pp_param_specs,
    )
    from odh_kubeflow_tpu.models.transformer import (
        pp_1f1b_value_and_grad,
        pp_loss_fn,
        to_pp_params,
    )
    from odh_kubeflow_tpu.parallel import MeshPlan, shard_batch

    plan = MeshPlan(fsdp=2, pp=2, tp=2)
    mesh = plan.build(jax.devices()[:8])
    cfg = TransformerConfig(
        vocab=64,
        d_model=32,
        n_layers=8,
        n_heads=4,
        n_kv_heads=2,
        d_ff=64,
        dtype=jnp.float32,
        use_flash=False,
        remat=False,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab)
    ref_loss, _ = jax.value_and_grad(loss_fn)(params, {"tokens": tokens}, cfg)

    # v=2 (4 layers/chunk) and v=4 (1 layer/chunk — deepest interleave of
    # an 8-layer stack at S=2): the schedule tables generalize over v, the
    # buffers stay O(S*v)
    for v in (2, 4):
        pp_params = to_pp_params(params, 2, cfg, mesh, n_chunks=v)
        specs = pp_param_specs(cfg, mesh, 2, n_chunks=v)
        pp_params = jax.tree_util.tree_map(
            lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), pp_params, specs
        )
        batch = shard_batch(mesh, {"tokens": tokens})

        g_loss, g_grads = jax.jit(jax.value_and_grad(
            lambda p: pp_loss_fn(p, batch, cfg, mesh, n_micro=4, n_chunks=v)
        ))(pp_params)
        f_loss, f_grads = jax.jit(
            lambda p, b: pp_1f1b_value_and_grad(
                p, b, cfg, mesh, n_micro=4, n_chunks=v
            )
        )(pp_params, batch)
        jax.block_until_ready(f_loss)

        assert np.allclose(float(f_loss), float(g_loss), atol=1e-6), v
        assert np.allclose(float(f_loss), float(ref_loss), atol=1e-5), v
        flat_g, _ = jax.tree_util.tree_flatten_with_path(g_grads)
        flat_f, _ = jax.tree_util.tree_flatten_with_path(f_grads)
        for (path_g, a), (path_f, b) in zip(flat_g, flat_f):
            assert path_g == path_f
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), atol=1e-6, rtol=1e-5,
                err_msg=f"v={v} {jax.tree_util.keystr(path_g)}",
            )


@pytest.mark.slow  # compile-heavy CPU-mesh parity (minutes): run via -m slow
def test_pp_sp_ring_inside_stages():
    """Long-context x pipeline: GPipe stages run ring attention on sequence
    shards (pipeline_apply seq_axis + _attention's seq_axis_bound path,
    per-shard rope positions from the bound sp coordinate) — contiguous at
    pp x sp x fsdp AND pp x sp x tp, zigzag (make_zigzag_batch sharding
    contiguously into the zigzag ring's local layout, explicit targets +
    loss_mask through pp_loss_fn). Loss and every gradient leaf match the
    non-pipelined single-device model; the 1F1B engines refuse the
    composition explicitly."""
    import numpy as np
    import pytest
    from jax.sharding import NamedSharding

    from odh_kubeflow_tpu.models import (
        TransformerConfig,
        init_params,
        loss_fn,
        pp_param_specs,
    )
    from odh_kubeflow_tpu.models.transformer import (
        pp_1f1b_value_and_grad,
        pp_loss_fn,
        to_pp_params,
    )
    from odh_kubeflow_tpu.parallel import MeshPlan, shard_batch

    base = dict(
        vocab=64, d_model=32, n_layers=4, n_heads=2, d_ff=64,
        dtype=jnp.float32, use_flash=False, remat=False,
    )
    cfg = TransformerConfig(seq_axis="sp", **base)
    cfg_ref = TransformerConfig(**base)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 64)
    ref_loss, ref_g = jax.value_and_grad(loss_fn)(
        params, {"tokens": tokens}, cfg_ref
    )

    for plan in (
        MeshPlan(fsdp=2, pp=2, sp=2),
        MeshPlan(pp=2, tp=2, sp=2),
    ):
        mesh = plan.build(jax.devices()[:8])
        pp_params = to_pp_params(params, 2, cfg, mesh)
        specs = pp_param_specs(cfg, mesh, 2)
        pp_params = jax.tree_util.tree_map(
            lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
            pp_params, specs,
        )
        batch = shard_batch(mesh, {"tokens": tokens})
        loss, g = jax.jit(
            lambda p, b: jax.value_and_grad(pp_loss_fn)(p, b, cfg, mesh, n_micro=2)
        )(pp_params, batch)
        assert np.allclose(float(loss), float(ref_loss), atol=1e-5), plan
        ref_pp_g = to_pp_params(ref_g, 2, cfg, mesh)
        for (pa, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(g)[0],
            jax.tree_util.tree_flatten_with_path(ref_pp_g)[0],
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4,
                err_msg=f"{plan} {jax.tree_util.keystr(pa)}",
            )

        with pytest.raises(NotImplementedError):
            pp_1f1b_value_and_grad(pp_params, batch, cfg, mesh, n_micro=2)

    # zigzag layout: the permuted batch shards contiguously into the
    # zigzag ring's [chunk r | chunk 2S-1-r] local layout; CE runs on the
    # batch's explicit targets/loss_mask and equals the natural-order loss
    # EXACTLY (make_zigzag_batch contract)
    from odh_kubeflow_tpu.models.transformer import make_zigzag_batch

    cfg_zz = TransformerConfig(seq_axis="sp", seq_layout="zigzag", **base)
    mesh = MeshPlan(fsdp=2, pp=2, sp=2).build(jax.devices()[:8])
    pp_params = to_pp_params(params, 2, cfg_zz, mesh)
    specs = pp_param_specs(cfg_zz, mesh, 2)
    pp_params = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        pp_params, specs,
    )
    batch = shard_batch(mesh, dict(make_zigzag_batch(tokens, sp=2)))
    loss, g = jax.jit(
        lambda p, b: jax.value_and_grad(pp_loss_fn)(p, b, cfg_zz, mesh, n_micro=2)
    )(pp_params, batch)
    assert np.allclose(float(loss), float(ref_loss), atol=1e-5)
    ref_pp_g = to_pp_params(ref_g, 2, cfg_zz, mesh)
    for (pa, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(g)[0],
        jax.tree_util.tree_flatten_with_path(ref_pp_g)[0],
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4,
            err_msg=f"zigzag {jax.tree_util.keystr(pa)}",
        )


def test_pipeline_aux_under_sp_warns_per_shard_approximation():
    """MoE router-aux under seq_axis is a documented per-shard approximation
    (parallel/pipeline.py aux notes): only dense pp x sp configs are
    parity-tested, so configuring an aux-carrying pipeline with sequence
    sharding must SAY SO — pipeline_apply emits a warning before tracing.
    Dense (with_aux=False) and unsharded-seq aux paths stay silent."""
    import warnings

    from odh_kubeflow_tpu.parallel import MeshPlan, pipeline_apply, stack_stages

    plan = MeshPlan(pp=2, sp=2)
    mesh = plan.build(jax.devices()[:4])
    d = 8
    w = jax.random.normal(jax.random.PRNGKey(0), (2, d, d)) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 4, d))  # (batch, seq, d)
    stages = stack_stages(w, 2)

    def stage_fn(stage_w, h):
        return jnp.tanh(h @ stage_w[0]), jnp.float32(0.0)

    def run(**kw):
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            try:
                pipeline_apply(stage_fn, stages, x, mesh, n_micro=2,
                               with_aux=True, **kw)
            except Exception:
                # the compute path may be unavailable in this environment
                # (jax API drift); the contract under test is the warning,
                # which fires before tracing
                pass
        return [w for w in rec if "per-shard" in str(w.message)]

    assert run(seq_axis="sp"), "aux + sp must warn about the per-shard aux"
    assert not run(), "aux without sequence sharding must stay silent"
