"""InferenceEndpoint serving subsystem (ISSUE 9): the continuous-batching
engine (greedy parity with generate(), slot recycling, backpressure, EOS),
the promotion flow (suspended notebook -> warm bind -> Loading with restore
verification -> Serving -> first token, one connected trace), drain
semantics, prewarmed pools, and the serving fault lane (slice preempted
mid-stream).

Deterministic tier-1 tests (marker: serving); ci/faults.sh reruns the fault
lane under RACECHECK=1 + INVCHECK=1.
"""
import time

import jax
import jax.numpy as jnp
import pytest

from odh_kubeflow_tpu.api.core import Container, Event, Node, Pod
from odh_kubeflow_tpu.api.gateway import HTTPRoute
from odh_kubeflow_tpu.api.inference import (
    InferenceEndpoint,
    NotebookRef,
    ServingSpec,
)
from odh_kubeflow_tpu.api.notebook import Notebook, TPUSpec
from odh_kubeflow_tpu.cluster import SimCluster
from odh_kubeflow_tpu.cluster.slicepool import (
    POOL_STATE_ANNOTATION,
    POOL_STATE_WARM,
    PoolPrewarmer,
    SlicePool,
    slice_pool_prewarmed_total,
)
from odh_kubeflow_tpu.controllers import (
    Config,
    InferenceEndpointReconciler,
    NotebookReconciler,
    ProbeStatusController,
    SuspendResumeController,
    constants as C,
)
from odh_kubeflow_tpu.models import TransformerConfig, generate, init_params
from odh_kubeflow_tpu.probe import sim_agent_behavior
from odh_kubeflow_tpu.runtime import Manager
from odh_kubeflow_tpu.serving import metrics as M
from odh_kubeflow_tpu.serving.engine import QueueFull, ServingEngine
from odh_kubeflow_tpu.tpu import GKE_NODEPOOL_LABEL
from odh_kubeflow_tpu.utils import tracing

pytestmark = pytest.mark.serving

NS = "serving"

FAST = Config(
    enable_culling=False,
    suspend_enabled=True,
    readiness_probe_period_s=0.15,
    suspend_checkpoint_window_s=1.5,
    resume_timeout_s=20.0,
    resume_max_attempts=4,
    reclaim_pending_grace_s=0.3,
    serving_loading_window_s=8.0,
    serving_drain_timeout_s=0.3,
)


# ---------------------------------------------------------------------------
# engine half (pure jax, no cluster)
# ---------------------------------------------------------------------------


TINY = TransformerConfig(
    vocab=97, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=128,
    max_seq=64, dtype=jnp.float32, use_flash=False, remat=False,
)


@pytest.fixture(scope="module")
def tiny_model():
    return init_params(jax.random.PRNGKey(0), TINY), TINY


def test_engine_greedy_parity_with_generate(tiny_model):
    """Continuous batching must change SCHEDULING, not numerics: with more
    requests than slots (forcing recycling + mid-flight admission), every
    request's greedy output equals the static generate() path's bitwise."""
    params, cfg = tiny_model
    eng = ServingEngine(params, cfg, max_slots=3, max_seq=64, max_queue_depth=16)
    prompts = [[1, 2, 3, 4], [5, 6, 7, 8], [9, 10, 11, 12], [13, 14, 15, 16],
               [17, 18, 19, 20]]
    handles = [eng.submit(p, max_new=6) for p in prompts]
    assert eng.run_until_idle(timeout=120)
    ref = jax.device_get(
        generate(params, jnp.asarray(prompts, jnp.int32), cfg, max_new=6,
                 max_seq=64)
    )
    for h, row in zip(handles, ref):
        assert h.result == "ok"
        assert h.tokens == [int(t) for t in row], "greedy parity broken"
        assert h.ttft_s is not None and h.ttft_s >= 0


def test_engine_mixed_lengths_recycle_slots(tiny_model):
    """The continuous-batching win, counted deterministically: mixed-length
    requests through S slots take far fewer whole-batch decode steps than
    the static-batch schedule (every sequence padded to the longest)."""
    params, cfg = tiny_model
    lengths = [2, 4, 8, 16]
    # decode_burst=1: every device step is one host step, so the step count
    # is exact and deterministic
    eng = ServingEngine(params, cfg, max_slots=2, max_seq=64,
                        max_queue_depth=8, decode_burst=1)
    handles = [eng.submit([1, 2, 3], max_new=n) for n in lengths]
    while not eng.idle():
        eng.step()
    for h, n in zip(handles, lengths):
        assert h.result == "ok" and len(h.tokens) == n
    # static batching at 2 slots: batches [2,4] and [8,16] each run to the
    # longest member -> 4 + 16 = 20 decode steps; continuous batching
    # backfills freed slots and stays strictly under that
    steps = eng.stats()["decode_steps"]
    assert steps < 20, f"continuous batching took {steps} steps (static: 20)"
    assert eng.stats()["generated_tokens"] == sum(lengths)


def test_engine_backpressure_rejects_past_queue_depth(tiny_model):
    params, cfg = tiny_model
    rejected0 = M.inference_requests_total.value(result="rejected")
    eng = ServingEngine(params, cfg, max_slots=1, max_seq=64, max_queue_depth=2)
    eng.submit([1], max_new=2)
    eng.submit([2], max_new=2)
    with pytest.raises(QueueFull):
        eng.submit([3], max_new=2)
    assert M.inference_requests_total.value(result="rejected") - rejected0 == 1
    assert eng.run_until_idle(timeout=60)
    # oversized requests are refused up front, not wedged in a slot
    with pytest.raises(ValueError):
        eng.submit([1] * 60, max_new=10)


def test_engine_eos_recycles_slot_early(tiny_model):
    params, cfg = tiny_model
    probe = ServingEngine(params, cfg, max_slots=1, max_seq=64)
    first = probe.submit([1, 2, 3, 4], max_new=1)
    assert probe.run_until_idle(timeout=60)
    eos = first.tokens[0]  # the model's actual first greedy token

    eng = ServingEngine(params, cfg, max_slots=1, max_seq=64, eos_id=eos)
    h = eng.submit([1, 2, 3, 4], max_new=32)
    assert eng.run_until_idle(timeout=60)
    assert h.result == "ok"
    assert h.tokens[-1] == eos
    assert len(h.tokens) < 32, "EOS did not stop the sequence early"


def test_engine_stop_cancels_fast(tiny_model):
    """Draining contract: stop() completes leftovers as canceled — requests
    fail fast instead of hanging on a dead engine."""
    params, cfg = tiny_model
    canceled0 = M.inference_requests_total.value(result="canceled")
    eng = ServingEngine(params, cfg, max_slots=1, max_seq=64, max_queue_depth=8)
    handles = [eng.submit([1, 2], max_new=30) for _ in range(3)]
    eng.step()  # one slot active, two queued
    eng.stop(drain_timeout_s=0.0)
    assert all(h.done.is_set() for h in handles)
    assert M.inference_requests_total.value(result="canceled") - canceled0 >= 2


def test_save_restore_round_trip_preserves_the_kernel(tiny_model, tmp_path):
    """Restore-side verification, workload half (ISSUE 9 satellite): an
    orbax save->restore round trip reproduces the exact state (checksum)
    and the exact decode behavior (logit fingerprint)."""
    orbax = pytest.importorskip("orbax.checkpoint")
    del orbax
    from odh_kubeflow_tpu.models import (
        logit_fingerprint,
        make_checkpoint_hook,
        make_restore_hook,
        state_checksum,
    )

    params, cfg = tiny_model
    state = {"params": params}
    save = make_checkpoint_hook(str(tmp_path), lambda: (7, state))
    ack = save()
    assert ack["step"] == 7
    assert ack["checksum"] == state_checksum(state)

    restore = make_restore_hook(str(tmp_path), lambda: state)
    rack = restore()
    assert rack["restored"] and rack["step"] == 7
    assert rack["checksum"] == ack["checksum"], "restored state diverged"
    # logit-parity probe: the model AS SERVED is unchanged by the round trip
    restored = pytest.importorskip("odh_kubeflow_tpu.models.checkpoint")
    rt = restored.restore_train_state(str(tmp_path), state)
    assert logit_fingerprint(rt["params"], cfg, [1, 2, 3, 4]) == \
        logit_fingerprint(params, cfg, [1, 2, 3, 4])


# ---------------------------------------------------------------------------
# controller half (sim cluster)
# ---------------------------------------------------------------------------


def build_env(config=FAST, slices=2):
    import json as _json

    cluster = SimCluster().start()
    cluster.add_tpu_pool("v5e", "v5e", "2x2", slices=slices)
    # deterministic /tpu/restore answers at the TRANSPORT: tests register
    # acks by pod-name substring BEFORE creating the workload. (Arming
    # per-incarnation agent restore hooks from a polling loop races the
    # controller's one-shot verification probe — the controller can win.)
    restore_acks = {}

    def http_get(url, timeout=10.0):
        if "/tpu/restore" in url:
            for key, ack in restore_acks.items():
                if key in url:
                    return 200, _json.dumps(ack).encode()
        return cluster.http_get(url, timeout=timeout)

    mgr = Manager(cluster.store)
    NotebookReconciler(mgr, config).setup()
    ProbeStatusController(mgr, config, http_get=cluster.http_get).setup()
    SuspendResumeController(mgr, config, http_get=http_get).setup()
    InferenceEndpointReconciler(mgr, config, http_get=http_get).setup()
    agents = {}
    cluster.add_pod_behavior(sim_agent_behavior(agents, duty=0.9))
    mgr.start()
    return cluster, mgr, agents, restore_acks


@pytest.fixture()
def env():
    cluster, mgr, agents, restore_acks = build_env()
    yield cluster, mgr, agents, restore_acks
    mgr.stop()
    cluster.stop()
    cluster.faults.clear()


def wait_for(fn, timeout=30, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(0.05)
    raise AssertionError(f"timeout: {msg}")


def mk_nb(name, priority=0):
    nb = Notebook()
    nb.metadata.name = name
    nb.metadata.namespace = NS
    nb.spec.template.spec.containers = [Container(name=name, image="jax:1")]
    nb.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2", priority=priority)
    return nb


def mk_ep(name, source=None, priority=0, drain_s=0.0):
    ep = InferenceEndpoint()
    ep.metadata.name = name
    ep.metadata.namespace = NS
    ep.spec.template.spec.containers = [Container(name=name, image="serve:1")]
    if source:
        ep.spec.notebook_ref = NotebookRef(name=source)
    else:
        ep.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2",
                              priority=priority)
    if priority and source:
        ep.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2",
                              priority=priority)
    ep.spec.serving = ServingSpec(max_batch_slots=2, max_queue_depth=8,
                                  max_seq=64, max_new_tokens=8,
                                  drain_timeout_s=drain_s)
    return ep


def ep_state(cluster, name):
    ep = cluster.client.get(InferenceEndpoint, NS, name)
    return ep.metadata.annotations.get(C.INFERENCE_STATE_ANNOTATION, "")


def ep_pods(cluster, name):
    return [
        p
        for p in cluster.client.list(
            Pod, namespace=NS, labels={C.INFERENCE_NAME_LABEL: name}
        )
        if not p.metadata.deletion_timestamp
    ]


def has_event(cluster, reason, involved=None):
    for e in cluster.client.list(Event, namespace=NS):
        if e.reason != reason:
            continue
        if involved is None or e.involved_object.name == involved:
            return True
    return False


def patch_persistent(cluster, kind, name, patch, attempts=40):
    from odh_kubeflow_tpu.apimachinery import ConflictError, TooManyRequestsError

    for i in range(attempts):
        try:
            cluster.client.patch(kind, NS, name, patch)
            return
        except (ConflictError, TooManyRequestsError):
            if i == attempts - 1:
                raise
            time.sleep(0.02)


def test_promotion_episode_warm_bind_trace_and_first_token(env, tiny_model):
    """THE acceptance episode: suspended notebook -> InferenceEndpoint
    Serving -> first token, through scheduler/slicepool/SLO machinery, one
    connected trace."""
    cluster, mgr, agents, restore_acks = env
    warm0 = M.inference_endpoint_promotions_total.value(bind="warm")
    ok0 = M.inference_restore_verifications_total.value(result="ok")

    # a notebook trains, checkpoints (with checksum), and suspends
    cluster.client.create(mk_nb("trainer"))
    wait_for(
        lambda: cluster.client.get(Notebook, NS, "trainer").status.tpu is not None
        and cluster.client.get(Notebook, NS, "trainer").status.tpu.mesh_ready,
        msg="notebook bring-up",
    )
    agents["trainer-0"].checkpoint_hook = lambda: {"step": 42, "checksum": "c0ffee"}
    pool_before = {
        n.metadata.labels.get(GKE_NODEPOOL_LABEL)
        for n in cluster.client.list(Node)
        for p in [cluster.client.get(Pod, NS, "trainer-0")]
        if p.spec.node_name == n.metadata.name
    }
    patch_persistent(
        cluster, Notebook, "trainer",
        {"metadata": {"annotations": {
            C.STOP_ANNOTATION: "2026-01-01T00:00:00Z",
            C.TPU_SUSPEND_STATE_ANNOTATION: "checkpointing",
        }}},
    )
    wait_for(
        lambda: cluster.client.get(Notebook, NS, "trainer")
        .metadata.annotations.get(C.TPU_SUSPEND_STATE_ANNOTATION) == "suspended"
        and not [p for p in cluster.client.list(
            Pod, namespace=NS, labels={C.NOTEBOOK_NAME_LABEL: "trainer"})
            if not p.metadata.deletion_timestamp],
        msg="notebook suspended, slice released warm",
    )
    nb = cluster.client.get(Notebook, NS, "trainer")
    assert nb.metadata.annotations.get(
        C.TPU_CHECKPOINT_CHECKSUM_ANNOTATION) == "c0ffee"

    # promote: the endpoint inherits shape + lineage and claims the warm
    # slice; the restored state reproduces the saved digest
    restore_acks["gemma-serve"] = {
        "restored": True, "step": 42, "checksum": "c0ffee",
    }
    cluster.client.create(mk_ep("gemma", source="trainer"))
    wait_for(lambda: ep_state(cluster, "gemma") == "serving",
             timeout=40, msg="endpoint Serving")

    ep = cluster.client.get(InferenceEndpoint, NS, "gemma")
    # promotion lineage + warm bind
    assert ep.metadata.annotations.get(
        C.INFERENCE_PROMOTED_FROM_ANNOTATION) == f"{NS}/trainer"
    assert ep.metadata.annotations.get(
        C.TPU_CHECKPOINT_CHECKSUM_ANNOTATION) == "c0ffee"
    assert M.inference_endpoint_promotions_total.value(bind="warm") - warm0 >= 1
    # the endpoint landed on the SAME slice the notebook released
    ep_pool = {
        cluster.client.get(Node, "", p.spec.node_name)
        .metadata.labels.get(GKE_NODEPOOL_LABEL)
        for p in ep_pods(cluster, "gemma") if p.spec.node_name
    }
    assert ep_pool and ep_pool == pool_before, (
        f"warm bind missed: endpoint on {ep_pool}, notebook was {pool_before}"
    )
    # restore verified against the inherited checksum
    assert M.inference_restore_verifications_total.value(result="ok") - ok0 >= 1
    # status + route + events
    assert ep.status.phase == "Serving"
    assert ep.status.url == f"/serving/{NS}/gemma"
    assert cluster.client.get(
        HTTPRoute, Config().controller_namespace,
        f"{NS}-gemma-serve"[:63],
    )
    assert has_event(cluster, "EndpointPromoted", "gemma")
    assert has_event(cluster, "EndpointServing", "gemma")
    # pool marks cleared: the slice is plainly owned by the endpoint's pods
    assert not any(
        n.metadata.annotations.get(POOL_STATE_ANNOTATION)
        for n in cluster.client.list(Node)
    )

    # FIRST TOKEN, one connected trace: the engine's per-request span joins
    # the endpoint.ready trace via the stamped traceparent
    traceparent = ep.metadata.annotations.get(C.TRACEPARENT_ANNOTATION)
    assert traceparent
    trace_id = tracing.parse_traceparent(traceparent)[0]
    params, cfg = tiny_model
    engine = ServingEngine(params, cfg, max_slots=2, max_seq=64)
    handle = engine.submit([1, 2, 3], max_new=3, traceparent=traceparent)
    assert engine.run_until_idle(timeout=60)
    assert handle.result == "ok" and handle.tokens

    spans = tracing.recent_spans(trace_id=trace_id)
    names = {s["name"] for s in spans}
    assert "endpoint.ready" in names, f"root missing from trace: {names}"
    assert "endpoint.promotion" in names
    assert "inference.request" in names
    assert all(s["trace_id"] == trace_id for s in spans)
    assert mgr.healthz()


def test_restore_mismatch_is_explicit_load_failure(env):
    cluster, mgr, agents, restore_acks = env
    mm0 = M.inference_restore_verifications_total.value(result="mismatch")
    cluster.client.create(mk_nb("src"))
    wait_for(
        lambda: cluster.client.get(Notebook, NS, "src").status.tpu is not None
        and cluster.client.get(Notebook, NS, "src").status.tpu.mesh_ready,
        msg="bring-up",
    )
    agents["src-0"].checkpoint_hook = lambda: {"step": 5, "checksum": "aaaa"}
    patch_persistent(
        cluster, Notebook, "src",
        {"metadata": {"annotations": {
            C.STOP_ANNOTATION: "2026-01-01T00:00:00Z",
            C.TPU_SUSPEND_STATE_ANNOTATION: "checkpointing",
        }}},
    )
    wait_for(lambda: cluster.client.get(Notebook, NS, "src")
             .metadata.annotations.get(C.TPU_SUSPEND_STATE_ANNOTATION)
             == "suspended", msg="suspended")

    # the restored state does NOT equal the saved one
    restore_acks["corrupt-serve"] = {
        "restored": True, "step": 5, "checksum": "bbbb",
    }
    cluster.client.create(mk_ep("corrupt", source="src"))
    wait_for(
        lambda: ep_state(cluster, "corrupt") == "load-failed",
        timeout=40, msg="explicit LoadFailed on checksum mismatch",
    )
    assert has_event(cluster, "LoadFailed", "corrupt")
    assert M.inference_restore_verifications_total.value(
        result="mismatch") - mm0 >= 1
    assert mgr.healthz()


def test_endpoint_drain_terminate_and_unstop(env):
    cluster, mgr, agents, _restore_acks = env
    cluster.client.create(mk_ep("draino"))
    wait_for(lambda: ep_state(cluster, "draino") == "serving", timeout=40,
             msg="cold endpoint Serving")
    route_ns = Config().controller_namespace

    patch_persistent(
        cluster, InferenceEndpoint, "draino",
        {"metadata": {"annotations": {
            C.STOP_ANNOTATION: "2026-01-01T00:00:00Z",
        }}},
    )
    wait_for(lambda: ep_state(cluster, "draino") == "terminated", timeout=40,
             msg="drained to Terminated")
    # route gone the moment draining started; pods drained; slice warm again
    from odh_kubeflow_tpu.apimachinery import NotFoundError
    with pytest.raises(NotFoundError):
        cluster.client.get(HTTPRoute, route_ns, f"{NS}-draino-serve"[:63])
    wait_for(lambda: not ep_pods(cluster, "draino"), msg="pods gone")
    # the event writes land one hop after the state flip
    wait_for(lambda: has_event(cluster, "EndpointDraining", "draino"),
             msg="EndpointDraining event")
    wait_for(lambda: has_event(cluster, "EndpointTerminated", "draino"),
             msg="EndpointTerminated event")
    wait_for(
        lambda: any(
            n.metadata.annotations.get(POOL_STATE_ANNOTATION) == POOL_STATE_WARM
            for n in cluster.client.list(Node)
        ),
        msg="drained slice released warm",
    )

    # unstop: Terminated self-heals into a fresh serving episode
    patch_persistent(
        cluster, InferenceEndpoint, "draino",
        {"metadata": {"annotations": {C.STOP_ANNOTATION: None}}},
    )
    wait_for(lambda: ep_state(cluster, "draino") == "serving", timeout=40,
             msg="unstopped back to Serving")
    assert mgr.healthz()


def test_serving_slice_preemption_recovers_without_repair_fight(env):
    """ci/faults.sh serving lane: preempt the serving slice mid-stream —
    the endpoint machine owns the whole recovery (Serving -> Loading ->
    Serving), the repair controller never fights it, nothing wedges."""
    cluster, mgr, agents, _restore_acks = env
    cluster.client.create(mk_ep("survivor"))
    wait_for(lambda: ep_state(cluster, "survivor") == "serving", timeout=40,
             msg="endpoint Serving")
    nodes = sorted(
        p.spec.node_name for p in ep_pods(cluster, "survivor")
        if p.spec.node_name
    )
    assert nodes
    for node in nodes:
        cluster.preempt_node(node, grace_s=0.05)
    # readiness lost -> back to Loading (or a full LoadFailed/retry loop);
    # never stuck in a lying Serving with dead hosts
    wait_for(
        lambda: ep_state(cluster, "survivor") != "serving",
        timeout=30, msg="Serving exited after slice preemption",
    )
    for node in nodes:
        cluster.restore_node(node)
    wait_for(lambda: ep_state(cluster, "survivor") == "serving", timeout=60,
             msg="endpoint recovered to Serving")
    assert has_event(cluster, "EndpointDegraded", "survivor")
    # the repair machine stood clear: no repair state ever landed on
    # anything (it only watches Notebooks) and no RepairFailed fired
    assert not has_event(cluster, "RepairFailed")
    assert mgr.healthz()


def test_prewarm_keeps_warm_slices_ahead_of_demand(env):
    """POOL_PREWARM satellite: free slices are parked warm ahead of demand
    and a promotion claims one (warm bind with no prior suspension)."""
    cluster, mgr, agents, _restore_acks = env
    prewarmed0 = slice_pool_prewarmed_total.value()
    warmer = PoolPrewarmer(
        cluster.client, "tpu-v5-lite-podslice", "2x2", target=1, period_s=0.2
    )
    assert warmer.tick() == 1
    assert slice_pool_prewarmed_total.value() - prewarmed0 == 1
    assert any(
        n.metadata.annotations.get(POOL_STATE_ANNOTATION) == POOL_STATE_WARM
        for n in cluster.client.list(Node)
    )
    # idempotent at target
    assert warmer.tick() == 0

    # a promotion with no suspended source still binds warm via the pool
    sp = SlicePool(cluster.client)
    entry = sp.claim("tpu-v5-lite-podslice", "2x2", f"{NS}/warm-claimer")
    assert entry is not None, "prewarmed slice was not claimable"
    sp.unclaim(entry.pool)


# ---------------------------------------------------------------------------
# in-pod HTTP entrypoint (ISSUE 10 satellite: `python -m odh_kubeflow_tpu.serving`)
# ---------------------------------------------------------------------------


def test_http_serving_entrypoint_smoke():
    """The in-pod HTTP front end to end: an engine built from the SERVING_*
    env (the controller's pod-template contract) behind ServingHTTPServer —
    /healthz gates, /generate returns the engine's tokens, /stats exposes
    the live counters, and bad input is a 400, all over a real socket."""
    import json as _json
    import urllib.error
    import urllib.request

    from odh_kubeflow_tpu.serving.server import (
        ServingHTTPServer,
        build_engine_from_env,
    )

    engine = build_engine_from_env({
        "SERVING_MAX_SLOTS": "2",
        "SERVING_MAX_SEQ": "64",
        "SERVING_MAX_QUEUE": "8",
        "SERVING_DECODE_BURST": "4",
    }).start()
    server = ServingHTTPServer(engine, host="127.0.0.1", port=0)
    host, port = server.start()
    base = f"http://{host}:{port}"
    try:
        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as r:
            assert r.status == 200 and _json.load(r)["ok"] is True

        req = urllib.request.Request(
            f"{base}/generate",
            data=_json.dumps({"prompt": [1, 2, 3], "max_new": 4}).encode(),
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            body = _json.load(r)
        assert body["result"] == "ok"
        assert len(body["tokens"]) == 4
        assert body["ttft_s"] >= 0.0
        # the wire path is the same engine: a direct submit agrees bitwise
        direct = engine.submit([1, 2, 3], max_new=4)
        assert direct.wait(timeout=60) and direct.tokens == body["tokens"]

        with urllib.request.urlopen(f"{base}/stats", timeout=10) as r:
            stats = _json.load(r)
        assert stats.get("completed", 0) >= 1 or stats

        bad = urllib.request.Request(
            f"{base}/generate", data=b'{"max_new": 4}', method="POST",
        )
        try:
            urllib.request.urlopen(bad, timeout=10)
            raise AssertionError("missing prompt must be a 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
        try:
            urllib.request.urlopen(f"{base}/nope", timeout=10)
            raise AssertionError("unknown path must be a 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        server.stop()
