"""e2e tier: the FULL operator (composition root, all controllers + webhook)
against the in-process cluster, mirroring the reference e2e suite's structure
(reference odh-notebook-controller/e2e/: setup fixtures incl. an auth/RBAC
variant, creation -> routing -> network policy -> StatefulSet -> auth sidecar
-> live HTTP traffic through the route backend -> culling; update blocking;
deletion cleanup). The reference needs a live OpenShift cluster and a 3-min
budget per resource; here the same flow runs in-process in seconds.
"""
import time

import pytest

from odh_kubeflow_tpu.api.apps import StatefulSet
from odh_kubeflow_tpu.api.core import Container, Pod, Service
from odh_kubeflow_tpu.api.gateway import HTTPRoute, ReferenceGrant
from odh_kubeflow_tpu.api.networking import NetworkPolicy
from odh_kubeflow_tpu.api.notebook import Notebook, TPUSpec
from odh_kubeflow_tpu.api.rbac import ClusterRoleBinding
from odh_kubeflow_tpu.apimachinery import NotFoundError
from odh_kubeflow_tpu.cluster import SimCluster
from odh_kubeflow_tpu.controllers import Config, constants as C
from odh_kubeflow_tpu.controllers.extension import auth_service_name, route_name
from odh_kubeflow_tpu.main import build_manager
from odh_kubeflow_tpu.probe import sim_agent_behavior
from odh_kubeflow_tpu.tpu import TPU_RESOURCE

CTRL_NS = "tpu-notebooks-system"
NS = "e2e-user"

# reference e2e: 3-min creation timeout / 10 s poll; in-process: 30 s / 50 ms
TIMEOUT = 30


def wait_for(fn, timeout=TIMEOUT, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            out = fn()
            if out:
                return out
        except NotFoundError:
            pass
        time.sleep(0.05)
    raise AssertionError(f"timeout: {msg}")


def gone(fn, timeout=TIMEOUT, msg="gone"):
    def check():
        try:
            fn()
            return False
        except NotFoundError:
            return True

    return wait_for(check, timeout=timeout, msg=msg)


@pytest.fixture(scope="module")
def ctx():
    """testContext analog (reference e2e/notebook_controller_setup_test.go:62-128):
    one cluster + full manager for the whole module; notebooks are fixtures."""
    cluster = SimCluster().start()
    cluster.add_cpu_pool("cpu", nodes=2)
    cluster.add_tpu_pool("v5e", "v5e", "2x2", slices=4)
    agents = {}
    cluster.add_pod_behavior(sim_agent_behavior(agents, duty=0.8))
    config = Config(
        controller_namespace=CTRL_NS,
        enable_culling=True,
        cull_idle_time_min=2.0 / 60.0,  # 2 s idle threshold
        idleness_check_period_min=0.1 / 60.0,
        set_pipeline_rbac=True,
    )
    mgr = build_manager(cluster.store, config, http_get=cluster.http_get)
    mgr.start()
    yield cluster, agents
    mgr.stop()
    cluster.stop()


def mk_nb(name, annotations=None, tpu=None):
    nb = Notebook()
    nb.metadata.name = name
    nb.metadata.namespace = NS
    nb.metadata.annotations = dict(annotations or {})
    nb.spec.template.spec.containers = [Container(name=name, image="jax:1")]
    nb.spec.tpu = tpu or TPUSpec(accelerator="v5e", topology="2x2")
    return nb


def test_creation_to_running_with_routing_and_policies(ctx):
    """reference notebook_creation_test.go:31-83 equivalent."""
    cluster, agents = ctx
    cluster.client.create(mk_nb("plain"))

    sts = wait_for(lambda: cluster.client.get(StatefulSet, NS, "plain"), msg="sts")
    c = sts.spec.template.spec.containers[0]
    assert (c.resources.requests or {}).get(TPU_RESOURCE) == "4"

    route = wait_for(
        lambda: cluster.client.get(HTTPRoute, CTRL_NS, route_name(mk_nb("plain"))),
        msg="httproute",
    )
    assert route.spec.rules[0].matches[0].path.value == f"/notebook/{NS}/plain"
    wait_for(lambda: cluster.client.get(ReferenceGrant, NS, "notebook-httproute-access"),
             msg="referencegrant")
    wait_for(lambda: cluster.client.get(NetworkPolicy, NS, "plain-ctrl-np"), msg="np")

    nb = wait_for(
        lambda: (
            lambda n: n if n.status.tpu and n.status.tpu.mesh_ready else None
        )(cluster.client.get(Notebook, NS, "plain")),
        msg="mesh ready",
    )
    assert nb.status.ready_replicas == 1
    assert nb.status.tpu.chips_visible == 4


def test_live_traffic_through_route_backend(ctx):
    """The reference drives real HTTP through the Gateway
    (e2e/helper_test.go:103-120); here the route's backendRef is resolved
    through cluster DNS to the pod's real socket."""
    cluster, agents = ctx
    route = wait_for(
        lambda: cluster.client.get(HTTPRoute, CTRL_NS, route_name(mk_nb("plain"))),
        msg="route",
    )
    backend = route.spec.rules[0].backend_refs[0]
    assert backend.namespace == NS
    url = f"http://{backend.name}.{NS}.svc.cluster.local:{backend.port}/api/kernels"
    status, body = wait_for(
        lambda: cluster.http_get(url), msg="traffic through backend"
    )
    assert status == 200
    assert b"[" in body  # Jupyter kernels JSON list


def test_auth_variant_sidecar_and_rbac_objects(ctx):
    """reference setup's RBAC fixture notebook + kube-rbac-proxy assertions."""
    cluster, agents = ctx
    cluster.client.create(
        mk_nb("secured", annotations={C.INJECT_AUTH_ANNOTATION: "true"})
    )
    sts = wait_for(lambda: cluster.client.get(StatefulSet, NS, "secured"), msg="sts")
    names = [c.name for c in sts.spec.template.spec.containers]
    assert "kube-rbac-proxy" in names

    wait_for(lambda: cluster.client.get(Service, NS, auth_service_name("secured")),
             msg="auth svc")
    nb = cluster.client.get(Notebook, NS, "secured")
    from odh_kubeflow_tpu.controllers.extension import auth_binding_name

    wait_for(lambda: cluster.client.get(ClusterRoleBinding, "", auth_binding_name(nb)),
             msg="crb")
    route = wait_for(
        lambda: cluster.client.get(HTTPRoute, CTRL_NS, route_name(nb)), msg="route"
    )
    # auth mode retargets the route to the proxy service
    assert route.spec.rules[0].backend_refs[0].name == auth_service_name("secured")
    wait_for(lambda: cluster.client.get(NetworkPolicy, NS, "secured-kube-rbac-proxy-np"),
             msg="proxy np")


def test_update_blocked_while_running(ctx):
    """reference notebook_update_test.go: webhook-caused diffs must not
    restart a running notebook; update-pending annotation records it."""
    cluster, agents = ctx
    wait_for(
        lambda: cluster.client.get(Notebook, NS, "plain").status.ready_replicas == 1,
        msg="running",
    )
    sts_uid = cluster.client.get(StatefulSet, NS, "plain").metadata.uid
    # flip auth on for a RUNNING notebook: webhook-caused podspec change
    cluster.client.patch(
        Notebook, NS, "plain",
        {"metadata": {"annotations": {C.INJECT_AUTH_ANNOTATION: "true"}}},
    )
    nb = wait_for(
        lambda: (
            lambda n: n
            if C.UPDATE_PENDING_ANNOTATION in n.metadata.annotations
            else None
        )(cluster.client.get(Notebook, NS, "plain")),
        msg="update-pending",
    )
    # podspec reverted: no sidecar materialized, same StatefulSet generation
    sts = cluster.client.get(StatefulSet, NS, "plain")
    assert [c.name for c in sts.spec.template.spec.containers] == ["plain"]
    assert sts.metadata.uid == sts_uid


def test_culling_stops_idle_notebook_and_frees_slice(ctx):
    """reference notebook_creation_test.go culling leg + TPU-native signal:
    idle kernels AND idle TPU -> replicas 0, slice freed."""
    cluster, agents = ctx
    cluster.client.create(mk_nb("dormant"))
    wait_for(
        lambda: cluster.client.get(Notebook, NS, "dormant").status.ready_replicas == 1,
        msg="running",
    )
    agent = agents["dormant-0"]
    agent.kernels.set_idle(time.time() - 3600)
    agent.monitor.duty = 0.0
    wait_for(
        lambda: C.STOP_ANNOTATION
        in cluster.client.get(Notebook, NS, "dormant").metadata.annotations,
        msg="stop annotation",
    )
    wait_for(
        lambda: cluster.client.get(StatefulSet, NS, "dormant").spec.replicas == 0,
        msg="scaled to zero",
    )
    gone(lambda: cluster.client.get(Pod, NS, "dormant-0"), msg="pod reclaimed")


def test_deletion_cleans_everything(ctx):
    """reference notebook_deletion_test.go: CR delete -> owned objects GC'd,
    cross-namespace + cluster-scoped objects finalizer-cleaned."""
    cluster, agents = ctx
    nb = cluster.client.get(Notebook, NS, "secured")
    from odh_kubeflow_tpu.controllers.extension import auth_binding_name

    crb_name = auth_binding_name(nb)
    cluster.client.delete(Notebook, NS, "secured")
    gone(lambda: cluster.client.get(Notebook, NS, "secured"), msg="nb gone")
    gone(lambda: cluster.client.get(StatefulSet, NS, "secured"), msg="sts gone")
    gone(lambda: cluster.client.get(HTTPRoute, CTRL_NS, route_name(nb)), msg="route gone")
    gone(lambda: cluster.client.get(ClusterRoleBinding, "", crb_name), msg="crb gone")
    # ReferenceGrant survives: "plain"/"dormant" still live in the namespace
    assert cluster.client.get(ReferenceGrant, NS, "notebook-httproute-access")


def test_pytorch_xla_runtime_env(ctx):
    """BASELINE config #4: torch-xla SPMD env injected end-to-end."""
    cluster, agents = ctx
    cluster.client.create(
        mk_nb("torch", tpu=TPUSpec(accelerator="v5e", topology="2x2",
                                   runtime="pytorch-xla"))
    )
    sts = wait_for(lambda: cluster.client.get(StatefulSet, NS, "torch"), msg="sts")
    env = {e.name: e.value for e in sts.spec.template.spec.containers[0].env if e.value}
    assert env["PJRT_DEVICE"] == "TPU"
    assert env["XLA_USE_SPMD"] == "1"
    assert "JAX_PLATFORMS" not in env


def test_long_name_notebook_reaches_mesh_ready(ctx):
    """VERDICT-r1 weak #6: a 63-char notebook name must still yield valid
    DNS labels (STS clamped at 52 chars like the reference's rule,
    notebook_controller.go:58-59; headless svc at 63) and reach mesh-ready
    end-to-end — multi-host coordinator addressing rides those names."""
    from odh_kubeflow_tpu.controllers.notebook import (
        hosts_service_name,
        statefulset_name,
    )

    cluster, agents = ctx
    long_name = ("workbench-" + "x" * 60)[:63]
    assert len(long_name) == 63
    cluster.client.create(mk_nb(long_name))

    sts_name = statefulset_name(long_name)
    assert len(sts_name) <= 52 and sts_name != long_name
    sts = wait_for(
        lambda: cluster.client.get(StatefulSet, NS, sts_name), msg="clamped sts"
    )
    assert len(sts.spec.service_name) <= 63
    assert sts.spec.service_name == hosts_service_name(long_name)

    nb = wait_for(
        lambda: (
            lambda n: n
            if n.status.tpu
            and n.status.tpu.mesh_ready
            # the STS-status mirror can trail the probe gate by a reconcile
            and n.status.ready_replicas == 1
            else None
        )(cluster.client.get(Notebook, NS, long_name)),
        msg="long-name mesh ready",
    )
    # pod DNS label sanity: {sts}-0 is a valid label
    assert len(f"{sts_name}-0") <= 63
