"""Remote-transport e2e: the FULL operator against the cluster OVER THE WIRE.

The VERDICT-r1 acceptance test for the real-cluster adapter: `build_manager`
runs unchanged on a RemoteStore, every informer watch is a streaming HTTP
connection, every reconcile write is a REST call, and admission happens
server-side via MutatingWebhookConfiguration -> HTTPS AdmissionReview callout
to the real NotebookWebhook. The cluster side (scheduler, kubelet, probe
agents) is the SimCluster acting on the same Store the ApiServer serves —
i.e. the manager process has NO in-process access to cluster state.

Reference anchors: managers connect via ctrl.GetConfigOrDie
(notebook-controller/main.go:79-94); webhook served over TLS
(odh main.go:213-227, suite_test.go:120-246).
"""
import time

import pytest

from odh_kubeflow_tpu.api.apps import StatefulSet
from odh_kubeflow_tpu.api.core import Container, Service
from odh_kubeflow_tpu.api.gateway import HTTPRoute
from odh_kubeflow_tpu.api.networking import NetworkPolicy
from odh_kubeflow_tpu.api.notebook import Notebook, TPUSpec
from odh_kubeflow_tpu.apimachinery import NotFoundError
from odh_kubeflow_tpu.cluster import Client, SimCluster
from odh_kubeflow_tpu.controllers import Config
from odh_kubeflow_tpu.controllers import constants as C
from odh_kubeflow_tpu.main import build_manager
from odh_kubeflow_tpu.probe import sim_agent_behavior

CTRL_NS = "tpu-notebooks-system"
NS = "remote-user"
TIMEOUT = 30


def wait_for(fn, timeout=TIMEOUT, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            out = fn()
            if out:
                return out
        except NotFoundError:
            pass
        time.sleep(0.05)
    raise AssertionError(f"timeout: {msg}")


def gone(fn, timeout=TIMEOUT, msg="gone"):
    def check():
        try:
            fn()
            return False
        except NotFoundError:
            return True

    return wait_for(check, timeout=timeout, msg=msg)


@pytest.fixture(scope="module")
def ctx():
    # ---- cluster side: sim nodes/kubelet/agents + the API server over TLS
    cluster = SimCluster().start()
    cluster.add_cpu_pool("cpu", nodes=2)
    cluster.add_tpu_pool("v5e", "v5e", "2x2", slices=4)
    agents = {}
    cluster.add_pod_behavior(sim_agent_behavior(agents, duty=0.8))

    config = Config(
        controller_namespace=CTRL_NS,
        enable_culling=True,
        cull_idle_time_min=2.0 / 60.0,
        idleness_check_period_min=0.1 / 60.0,
        set_pipeline_rbac=True,
    )

    # ---- manager side: everything over the wire from here on, via the
    # SHARED stack builder (same admission path as loadtest --remote)
    from odh_kubeflow_tpu.cluster.remote_fixture import build_remote_stack

    teardown = []
    try:
        _, remote, _ = build_remote_stack(
            cluster.store, config, teardown, token="e2e-token"
        )
        mgr = build_manager(remote, config, http_get=cluster.http_get)
        mgr.start()
    except Exception:
        # a partially-started TLS stack must not outlive a failed fixture
        for fn in reversed(teardown):
            fn()
        cluster.stop()
        raise
    client = Client(remote)
    yield cluster, client, agents
    mgr.stop()
    for fn in reversed(teardown):
        fn()
    cluster.stop()


def mk_nb(name, annotations=None):
    nb = Notebook()
    nb.metadata.name = name
    nb.metadata.namespace = NS
    nb.metadata.annotations = dict(annotations or {})
    nb.spec.template.spec.containers = [Container(name=name, image="jax:1")]
    nb.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2")
    return nb


def test_notebook_lifecycle_over_the_wire(ctx):
    cluster, client, agents = ctx
    client.create(mk_nb("wire"))

    # webhook ran over HTTPS: the stored object carries the lock... and the
    # extension controller (also over the wire) later removes it
    sts = wait_for(lambda: client.get(StatefulSet, NS, "wire"), msg="sts")
    assert sts.spec.template.spec.node_selector.get("cloud.google.com/gke-tpu-accelerator")
    wait_for(lambda: client.get(Service, NS, "wire"), msg="svc")
    wait_for(
        lambda: [r for r in client.list(HTTPRoute, namespace=CTRL_NS)
                 if r.metadata.labels.get("notebook-name") == "wire"],
        msg="route",
    )
    wait_for(lambda: client.get(NetworkPolicy, NS, "wire-ctrl-np"), msg="netpol")
    nb = wait_for(
        lambda: client.get(Notebook, NS, "wire").status.ready_replicas == 1
        and client.get(Notebook, NS, "wire"),
        msg="ready",
    )
    assert C.STOP_ANNOTATION not in nb.metadata.annotations


def test_culling_and_wakeup_over_the_wire(ctx):
    cluster, client, agents = ctx
    client.create(mk_nb("dozy"))
    wait_for(
        lambda: client.get(Notebook, NS, "dozy").status.ready_replicas == 1,
        msg="ready",
    )
    # make the workload idle: stale kernels AND zero TPU duty-cycle
    agent = agents["dozy-0"]
    agent.kernels.set_idle(time.time() - 3600)
    agent.monitor.duty = 0.0
    nb = wait_for(
        lambda: C.STOP_ANNOTATION
        in client.get(Notebook, NS, "dozy").metadata.annotations
        and client.get(Notebook, NS, "dozy"),
        msg="culled",
    )
    assert nb.metadata.annotations[C.STOP_ANNOTATION] != C.RECONCILIATION_LOCK_VALUE
    wait_for(
        lambda: client.get(StatefulSet, NS, "dozy").spec.replicas == 0,
        msg="scaled to zero",
    )


def test_deletion_cleanup_over_the_wire(ctx):
    cluster, client, agents = ctx
    client.create(mk_nb("doomed"))
    wait_for(lambda: client.get(StatefulSet, NS, "doomed"), msg="sts")
    wait_for(
        lambda: [r for r in client.list(HTTPRoute, namespace=CTRL_NS)
                 if r.metadata.labels.get("notebook-name") == "doomed"],
        msg="route",
    )
    client.delete(Notebook, NS, "doomed")
    gone(lambda: client.get(Notebook, NS, "doomed"), msg="nb gone")
    gone(lambda: client.get(StatefulSet, NS, "doomed"), msg="sts gone")
    wait_for(
        lambda: not [r for r in client.list(HTTPRoute, namespace=CTRL_NS)
                     if r.metadata.labels.get("notebook-name") == "doomed"],
        msg="route gone",
    )
