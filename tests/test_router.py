"""Health-aware token router (serving/router.py, ISSUE 16): signal-driven
picking, breaker ejection with bounded re-admission, cross-replica retries
for idempotent failures, tail hedging that cancels the loser, route-first
drain, cold-wake on a parked fleet, and the serving priority-level 429.

The router is duck-typed over engine-like backends (submit/stats/cancel is
the whole contract), so these tests drive it with scripted fakes — the
loadtest multi-replica tier exercises the same router against the real
ServingEngine. Deterministic tier-1 tests (marker: router); the
ci/faults.sh router lane reruns these under REPEAT + RACECHECK=1 +
INVCHECK=1 + DEPLOYGUARD=1.
"""
import random
import threading
import time

import pytest

from odh_kubeflow_tpu.cluster.flowcontrol import (
    FlowController,
    FlowSchema,
    PriorityLevel,
    current_flow,
)
from odh_kubeflow_tpu.serving import metrics as sm
from odh_kubeflow_tpu.serving.engine import QueueFull, RequestHandle
from odh_kubeflow_tpu.serving.router import RouteResult, TokenRouter

pytestmark = pytest.mark.router


class FakeEngine:
    """Engine-like backend with scripted behavior. mode:
    ok         — submit returns an already-completed handle
    hang       — submit returns an open handle (complete via .complete())
    error      — submit raises ConnectionError
    queue_full — submit raises QueueFull
    canceled   — submit returns a handle already completed `canceled`
    """

    def __init__(self, mode="ok", queued=0, active=0, slots=4, ttft=0.0):
        self.mode = mode
        self.queued = queued
        self.active = active
        self.slots = slots
        self.ttft = ttft
        self.submitted = []
        self.canceled = []
        self._n = 0

    def stats(self):
        return {
            "queued": self.queued,
            "active_slots": self.active,
            "max_slots": self.slots,
        }

    def submit(self, prompt, max_new, traceparent=None):
        if self.mode == "error":
            raise ConnectionError("replica down")
        if self.mode == "queue_full":
            raise QueueFull("admission queue full")
        self._n += 1
        h = RequestHandle(
            id=self._n, prompt=list(prompt), max_new=max_new,
            submitted=time.monotonic(), traceparent=traceparent,
        )
        self.submitted.append(h)
        if self.mode == "ok":
            self.complete(h, "ok")
        elif self.mode == "canceled":
            self.complete(h, "canceled")
        return h

    def complete(self, h, result="ok"):
        if result == "ok":
            h.tokens = [1, 2, 3]
            h.ttft_s = self.ttft
        h.result = result
        h.done.set()

    def cancel(self, h):
        if h.done.is_set():
            return False
        self.canceled.append(h)
        self.complete(h, "canceled")
        return True


class FakeClock:
    """Deterministic monotonic clock; the router's injected sleep advances
    it so backoff/cooldown logic runs without wall time."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def sleep(self, s):
        self.t += s

    def advance(self, s):
        self.t += s


def mk_router(engines, **kw):
    clk = kw.pop("clk", None) or FakeClock()
    kw.setdefault("clock", clk)
    kw.setdefault("sleep", clk.sleep)
    kw.setdefault("rng", random.Random(0))
    router = TokenRouter(endpoint="ep", **kw)
    for i, eng in enumerate(engines):
        router.add_replica(i, eng)
    return router, clk


# ---------------------------------------------------------------------------
# picking
# ---------------------------------------------------------------------------


def test_pick_routes_to_least_loaded_replica():
    busy = FakeEngine(queued=5, active=4, slots=4)
    idle = FakeEngine(queued=0, active=0, slots=4)
    router, _ = mk_router([busy, idle])
    assert router.pick() == 1
    res = router.generate([1, 2], max_new=4)
    assert isinstance(res, RouteResult)
    assert res.replica == 1 and res.retries == 0 and not res.hedged
    assert idle.submitted and not busy.submitted


def test_observed_ttft_tail_penalizes_slow_replica():
    slow = FakeEngine(ttft=5.0)
    fast = FakeEngine(ttft=0.001)
    router, _ = mk_router([slow, fast])
    # seed the router's per-replica TTFT view through real requests
    for idx in (0, 1):
        for _ in range(4):
            router._finish(router._replicas[idx], slow.submit([1], 1)
                           if idx == 0 else fast.submit([1], 1))
    assert router.pick() == 1


# ---------------------------------------------------------------------------
# ejection + bounded re-admission
# ---------------------------------------------------------------------------


def test_ejection_then_bounded_readmission():
    flaky = FakeEngine(queued=0)  # most attractive score
    steady = FakeEngine(queued=2)
    router, clk = mk_router(
        [flaky, steady], breaker_failure_threshold=2, breaker_cooldown_s=10.0,
    )
    router.note_probe_failure(0)
    assert router.ejected() == []  # one failure is below the threshold
    router.note_probe_failure(0)
    assert router.ejected() == [0]
    # ejected replica leaves rotation even though its score is best
    assert router.pick() == 1
    before = sm.inference_router_ejections_total.value(action="readmit")
    # inside the cooldown the breaker stays shut
    clk.advance(5.0)
    assert router.pick() == 1
    # past the cooldown: exactly one half-open trial is admitted, and a
    # successful request through it re-admits the replica
    clk.advance(6.0)
    res = router.generate([1], max_new=2)
    assert res.replica == 0
    assert router.ejected() == []
    assert sm.inference_router_ejections_total.value(action="readmit") == before + 1


def test_failed_halfopen_trial_reejects_with_longer_cooldown():
    dead = FakeEngine(mode="error", queued=0)
    ok = FakeEngine(queued=3)
    router, clk = mk_router(
        [dead, ok], breaker_failure_threshold=1, breaker_cooldown_s=2.0,
        max_retries=1,
    )
    res = router.generate([1], max_new=2)  # fails on 0, retried on 1
    assert res.replica == 1 and res.retries == 1
    assert router.ejected() == [0]
    clk.advance(2.5)  # half-open trial admitted...
    res = router.generate([1], max_new=2)  # ...fails again -> re-ejected
    assert res.replica == 1
    clk.advance(2.5)  # doubled cooldown: still shut
    assert router.pick() == 1


# ---------------------------------------------------------------------------
# retries: idempotent failures move to a DIFFERENT replica
# ---------------------------------------------------------------------------


def test_error_retries_on_different_replica():
    broken = FakeEngine(mode="error", queued=0)
    healthy = FakeEngine(queued=1)
    router, _ = mk_router([broken, healthy], breaker_failure_threshold=1)
    res = router.generate([1, 2], max_new=4)
    assert res.replica == 1 and res.retries == 1
    assert healthy.submitted and not healthy.canceled
    assert router.ejected() == [0]  # the error also fed the breaker


def test_queue_full_retries_without_ejecting():
    full = FakeEngine(mode="queue_full", queued=0)
    healthy = FakeEngine(queued=1)
    router, _ = mk_router([full, healthy], breaker_failure_threshold=1)
    res = router.generate([1, 2], max_new=4)
    assert res.replica == 1 and res.retries == 1
    assert router.ejected() == []  # full is load, not failure


def test_canceled_midflight_retries_elsewhere():
    torn_down = FakeEngine(mode="canceled", queued=0)
    healthy = FakeEngine(queued=1)
    router, _ = mk_router([torn_down, healthy], breaker_failure_threshold=3)
    res = router.generate([1, 2], max_new=4)
    assert res.replica == 1 and res.retries == 1


def test_retry_budget_exhausts_to_the_callers_error():
    router, _ = mk_router(
        [FakeEngine(mode="error"), FakeEngine(mode="error")],
        breaker_failure_threshold=100, max_retries=2,
    )
    with pytest.raises(ConnectionError):
        router.generate([1], max_new=2)


def test_backoff_is_jittered_exponential_and_capped():
    router, clk = mk_router([FakeEngine()], max_retries=3)
    t0 = clk.t
    router._backoff(1)
    first = clk.t - t0
    assert 0.005 <= first <= 0.01  # base 10ms, jitter in [0.5, 1.0]
    t1 = clk.t
    router._backoff(10)  # far past the cap
    assert clk.t - t1 <= 0.25


# ---------------------------------------------------------------------------
# hedging
# ---------------------------------------------------------------------------


def test_hedge_launches_and_winner_cancels_the_loser():
    stuck = FakeEngine(mode="hang", queued=0)  # preferred, never finishes
    quick = FakeEngine(queued=1)
    router, _ = mk_router(
        [stuck, quick], hedge_after_s=0.001,
        clock=time.monotonic, sleep=time.sleep, clk=FakeClock(),
    )
    # real clock: hedging polls both handles on wall time
    router.clock = time.monotonic
    router.sleep = time.sleep
    res = router.generate([1, 2], max_new=4, wait_timeout_s=5.0)
    assert res.hedged and res.hedge_won and res.replica == 1
    # the loser was canceled, not left decoding a duplicate answer
    assert stuck.canceled and stuck.canceled[0].result == "canceled"
    assert quick.submitted[0].result == "ok"


def test_hedge_primary_win_cancels_the_hedge():
    primary = FakeEngine(mode="hang", queued=0)
    backup = FakeEngine(mode="hang", queued=1)
    router, _ = mk_router(
        [primary, backup], hedge_after_s=0.001, clk=FakeClock(),
    )
    router.clock = time.monotonic
    router.sleep = time.sleep
    done = {}

    def run():
        done["res"] = router.generate([1], max_new=2, wait_timeout_s=5.0)

    t = threading.Thread(target=run)
    t.start()
    deadline = time.monotonic() + 5.0
    while not (primary.submitted and backup.submitted):
        assert time.monotonic() < deadline, "hedge never launched"
        time.sleep(0.002)
    primary.complete(primary.submitted[0], "ok")
    t.join(5.0)
    res = done["res"]
    assert res.hedged and not res.hedge_won and res.replica == 0
    assert backup.canceled  # the hedge was canceled


# ---------------------------------------------------------------------------
# drain: no new picks, in-flight work completes
# ---------------------------------------------------------------------------


def test_draining_replica_takes_no_new_picks_but_finishes_inflight():
    draining = FakeEngine(mode="hang", queued=0)
    rest = FakeEngine(queued=1)
    router, _ = mk_router([draining, rest])
    router.clock = time.monotonic
    router.sleep = time.sleep
    done = {}

    def run():
        done["res"] = router.generate([1], max_new=2, wait_timeout_s=5.0)

    t = threading.Thread(target=run)
    t.start()
    deadline = time.monotonic() + 5.0
    while not draining.submitted:
        assert time.monotonic() < deadline
        time.sleep(0.002)
    # the drain starts with a request in flight on replica 0
    router.set_draining(0)
    assert router.pick() == 1  # new traffic avoids the draining replica
    res2 = router.generate([3], max_new=2)
    assert res2.replica == 1
    # the in-flight request is NOT dropped: it completes normally
    draining.complete(draining.submitted[0], "ok")
    t.join(5.0)
    assert done["res"].replica == 0 and done["res"].handle.result == "ok"
    router.set_draining(0, False)
    assert router.pick() == 0  # back in rotation after the drain withdraws


# ---------------------------------------------------------------------------
# cold-wake + admission
# ---------------------------------------------------------------------------


def test_cold_wake_fires_rate_limited_under_router_flow():
    wakes = []

    def wake():
        wakes.append(current_flow())

    router, clk = mk_router([], cold_wake=wake)
    clk.advance(10.0)
    with pytest.raises(QueueFull):
        router.generate([1], max_new=2)
    assert wakes == ["token-router"]  # flow-classified manager traffic
    with pytest.raises(QueueFull):
        router.generate([1], max_new=2)
    assert len(wakes) == 1  # rate-limited inside the cooldown
    clk.advance(2.0)
    with pytest.raises(QueueFull):
        router.generate([1], max_new=2)
    assert len(wakes) == 2


def test_all_replicas_ejected_sheds_and_wakes_nobody_without_callback():
    eng = FakeEngine(queued=0)
    router, _ = mk_router([eng], breaker_failure_threshold=1)
    router.note_probe_failure(0)
    before = sm.inference_router_picks_total.value(result="no_replica")
    with pytest.raises(QueueFull):
        router.generate([1], max_new=2)
    assert sm.inference_router_picks_total.value(result="no_replica") == before + 1


def test_router_inflight_bound_sheds():
    stuck = FakeEngine(mode="hang")
    router, _ = mk_router([stuck], max_inflight=1)
    router.clock = time.monotonic
    router.sleep = time.sleep
    t = threading.Thread(
        target=lambda: router.generate([1], max_new=2, wait_timeout_s=5.0)
    )
    t.start()
    deadline = time.monotonic() + 5.0
    while not stuck.submitted:
        assert time.monotonic() < deadline
        time.sleep(0.002)
    with pytest.raises(QueueFull):
        router.generate([2], max_new=2)
    stuck.complete(stuck.submitted[0], "ok")
    t.join(5.0)


@pytest.mark.flowcontrol
def test_requests_hold_a_seat_in_the_serving_priority_level():
    fc = FlowController(
        schemas=[
            FlowSchema("serving-requests", "serving",
                       kinds=("InferenceRequest",)),
            FlowSchema("catch-all", "default"),
        ],
        levels=[
            PriorityLevel("serving", seats=1, queue_length=0,
                          queue_timeout_s=0.05),
            PriorityLevel("default", seats=4),
        ],
    )
    # the default controller classifies InferenceRequest traffic into the
    # serving level regardless of the per-endpoint flow name
    assert FlowController().classify(
        "serving:ep", verb="create", kind="InferenceRequest"
    ).name == "serving"
    router, _ = mk_router([FakeEngine()], flow_controller=fc)
    router.generate([1], max_new=2)
    assert fc.summary()["serving"]["dispatched"] == 1
    hog = fc.admit("serving:other", verb="create", kind="InferenceRequest")
    try:
        before = sm.inference_router_picks_total.value(result="shed")
        with pytest.raises(QueueFull):  # 429 idiom at the router boundary
            router.generate([1], max_new=2)
        assert sm.inference_router_picks_total.value(result="shed") == before + 1
        assert fc.summary()["serving"]["rejected"] >= 1
    finally:
        hog.release()


# ---------------------------------------------------------------------------
# the seeded router bad day (cluster/faults.py — the ci/faults.sh router
# lane's chaos schedule)
# ---------------------------------------------------------------------------


class StubCluster:
    """Just enough cluster for the schedule: preemption calls are recorded,
    probe partitions + the control-plane rules land in a real injector."""

    def __init__(self):
        from odh_kubeflow_tpu.cluster.faults import FaultInjector

        self.faults = FaultInjector()
        self.preempted = []

    def preempt_node(self, name, grace_s=0.5):
        self.preempted.append((name, grace_s))


@pytest.mark.faults
def test_seeded_router_bad_day_is_deterministic_and_enacts_the_plan():
    from odh_kubeflow_tpu.cluster.faults import seeded_router_bad_day

    replica_nodes = {
        0: ["node-r0-a", "node-r0-b"],
        1: ["node-r1-a", "node-r1-b"],
        2: ["node-r2-a", "node-r2-b"],
    }
    plans = []
    for _ in range(2):
        cluster = StubCluster()
        plans.append(
            seeded_router_bad_day(cluster, seed=7,
                                  replica_nodes=replica_nodes)
        )
    assert plans[0] == plans[1]  # same seed -> identical bad day
    plan = plans[0]
    # one whole gang is the preemption victim — every one of its hosts
    assert plan["killed_replica"] in replica_nodes
    assert plan["preempted"] == sorted(replica_nodes[plan["killed_replica"]])
    assert [n for n, _ in cluster.preempted] == plan["preempted"]
    # the slow replica SURVIVES (the router must route around it, not lose it)
    assert plan["slow_replica"] != plan["killed_replica"]
    assert plan["slow_factor"] > 1.0
    # probe flaps are count-bounded rules on surviving hosts
    assert plan["probe_flap_hosts"]
    for host in plan["probe_flap_hosts"]:
        assert host not in plan["preempted"]
    # the control-plane schedule rode along (seeded_bad_day rules installed)
    assert len(cluster.faults._rules) > len(plan["probe_flap_hosts"])


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------


@pytest.mark.observability
def test_router_metric_families_render():
    router, _ = mk_router([FakeEngine()])
    router.generate([1], max_new=2)
    text = sm.global_registry.render()
    for family in (
        "inference_router_picks_total",
        "inference_router_retries_total",
        "inference_router_hedges_total",
        "inference_router_ejections_total",
        "inference_router_added_latency_seconds_bucket",
    ):
        assert family in text, family
    assert sm.inference_router_picks_total.value(result="ok") >= 1


# ---------------------------------------------------------------------------
# trace stitching (ISSUE 17 satellite): router -> replica -> first token is
# ONE connected trace tree in the tracing buffer
# ---------------------------------------------------------------------------


@pytest.fixture
def traced():
    from odh_kubeflow_tpu.utils import tracing

    tracing.set_enabled(True)
    tracing.clear()
    yield tracing
    tracing.clear()


def test_routed_request_is_a_single_trace_tree(traced):
    """An incoming traceparent flows through the router's envelope span into
    the replica submit, so the REAL engine's inference.request (which carries
    the first-token latency) lands in the same tree: incoming -> router.request
    -> {router.pick, inference.request}."""
    import jax
    import jax.numpy as jnp

    from odh_kubeflow_tpu.models import TransformerConfig, init_params
    from odh_kubeflow_tpu.serving.engine import ServingEngine

    cfg = TransformerConfig(
        vocab=64, d_model=16, n_layers=1, n_heads=2, d_ff=32, max_seq=32,
        dtype=jnp.float32, use_flash=False, remat=False,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    engine = ServingEngine(params, cfg, max_slots=2, max_seq=32).start()
    try:
        router = TokenRouter(endpoint="ns/ep")
        router.add_replica(0, engine)
        trace_id = traced.new_trace_id()
        caller_span = traced.new_span_id()
        incoming = traced.format_traceparent(trace_id, caller_span)
        res = router.generate(
            [1, 2, 3], max_new=2, wait_timeout_s=30, traceparent=incoming
        )
        assert res.handle.result == "ok"
    finally:
        engine.stop()

    spans = {s.name: s for s in traced.global_buffer.spans(trace_id=trace_id)}
    assert {"router.request", "router.pick", "inference.request"} <= set(spans)
    # every span joined the CALLER's trace — no orphan trace ids anywhere
    for s in spans.values():
        assert s.trace_id == trace_id, s.name
    envelope = spans["router.request"]
    assert envelope.parent_id == caller_span
    assert envelope.attributes["result"] == "ok"
    # pick + the engine-side request both hang off the router envelope
    assert spans["router.pick"].parent_id == envelope.span_id
    assert spans["inference.request"].parent_id == envelope.span_id
    # the engine span is the first-token record: ttft rode the same tree
    assert spans["inference.request"].attributes["ttft_s"] is not None
    assert spans["inference.request"].attributes["superseded"] is False


def test_routed_failure_envelope_and_retry_spans_share_the_trace(traced):
    broken = FakeEngine(mode="error", queued=0)
    healthy = FakeEngine(queued=1)
    router, _ = mk_router([broken, healthy], breaker_failure_threshold=1)
    trace_id = traced.new_trace_id()
    incoming = traced.format_traceparent(trace_id, traced.new_span_id())
    res = router.generate([1, 2], max_new=4, traceparent=incoming)
    assert res.replica == 1 and res.retries == 1
    spans = traced.global_buffer.spans(trace_id=trace_id)
    by_name = {}
    for s in spans:
        by_name.setdefault(s.name, []).append(s)
    envelope = by_name["router.request"][0]
    # the cross-replica retry is a visible child of the routed request
    assert by_name["router.retry"][0].parent_id == envelope.span_id
    assert by_name["router.retry"][0].attributes["reason"] == "error"
    assert len(by_name["router.pick"]) == 2  # original + retry pick


def test_hedged_loser_is_canceled_superseded_in_the_same_trace(traced):
    """The hedge loser's cancellation stays inside the routed request's trace
    but is explicitly marked: the router sets handle.superseded BEFORE the
    cancel, and the real engine's completion span carries the tag."""
    import jax
    import jax.numpy as jnp

    from odh_kubeflow_tpu.models import TransformerConfig, init_params
    from odh_kubeflow_tpu.serving.engine import ServingEngine

    # router half: the FakeEngine hedge race proves the loser handle is
    # tagged superseded before cancel
    stuck = FakeEngine(mode="hang", queued=0)  # preferred, never finishes
    quick = FakeEngine(queued=1)
    router, _ = mk_router([stuck, quick], hedge_after_s=0.001, clk=FakeClock())
    router.clock = time.monotonic  # hedging polls both handles on wall time
    router.sleep = time.sleep
    trace_id = traced.new_trace_id()
    incoming = traced.format_traceparent(trace_id, traced.new_span_id())
    res = router.generate(
        [1], max_new=2, wait_timeout_s=5.0, traceparent=incoming
    )
    assert res.hedged and res.hedge_won
    assert stuck.canceled and stuck.canceled[0].superseded is True
    hedge_spans = [
        s for s in traced.global_buffer.spans(trace_id=trace_id)
        if s.name == "router.hedge"
    ]
    assert hedge_spans and hedge_spans[0].attributes["hedge"] == 1

    # engine half: a superseded cancel through the REAL engine records an
    # inference.request span tagged superseded=True in the same trace
    cfg = TransformerConfig(
        vocab=64, d_model=16, n_layers=1, n_heads=2, d_ff=32, max_seq=32,
        dtype=jnp.float32, use_flash=False, remat=False,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    engine = ServingEngine(params, cfg, max_slots=2, max_seq=32)
    try:
        ctx = traced.format_traceparent(trace_id, traced.new_span_id())
        handle = engine.submit([1, 2], max_new=8, traceparent=ctx)
        handle.superseded = True  # exactly what the router does to a loser
        assert engine.cancel(handle)
    finally:
        engine.stop()
    loser_spans = [
        s for s in traced.global_buffer.spans(trace_id=trace_id)
        if s.name == "inference.request"
    ]
    assert loser_spans
    assert loser_spans[-1].attributes["superseded"] is True
    assert loser_spans[-1].attributes["result"] == "canceled"
