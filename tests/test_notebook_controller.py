"""Core NotebookReconciler against the full SimCluster: the Milestone-A
end-to-end slice (SURVEY §7 step 2) plus stop/restart/status semantics."""
import time

import pytest

from odh_kubeflow_tpu.api.apps import StatefulSet
from odh_kubeflow_tpu.api.core import Container, Event, Pod, Service
from odh_kubeflow_tpu.api.notebook import Notebook, TPUSpec
from odh_kubeflow_tpu.apimachinery import NotFoundError
from odh_kubeflow_tpu.cluster import SimCluster
from odh_kubeflow_tpu.controllers import (
    Config,
    EventMirrorController,
    NotebookReconciler,
    ProbeStatusController,
    constants as C,
)
from odh_kubeflow_tpu.probe import sim_agent_behavior
from odh_kubeflow_tpu.runtime import Manager
from odh_kubeflow_tpu.tpu import TPU_RESOURCE


@pytest.fixture()
def env():
    """SimCluster + a separate product manager (mirrors the reference's
    two-process layout against one API server)."""
    cluster = SimCluster().start()
    agents = {}
    cluster.add_pod_behavior(sim_agent_behavior(agents))
    cfg = Config(readiness_probe_period_s=0.3)
    mgr = Manager(cluster.store)
    NotebookReconciler(mgr, cfg).setup()
    EventMirrorController(mgr).setup()
    ProbeStatusController(mgr, cfg, http_get=cluster.http_get).setup()
    mgr.start()
    yield cluster, mgr
    mgr.stop()
    cluster.stop()


def mk_notebook(name, ns="user", tpu=None, image="jupyter:latest"):
    nb = Notebook()
    nb.metadata.name = name
    nb.metadata.namespace = ns
    nb.spec.template.spec.containers = [Container(name=name, image=image)]
    if tpu:
        nb.spec.tpu = tpu
    return nb


def wait_for(fn, timeout=10, msg="condition"):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            out = fn()
            if out:
                return out
            last = out
        except (NotFoundError, AssertionError) as e:
            last = e
        time.sleep(0.05)
    raise AssertionError(f"timeout waiting for {msg}: last={last!r}")


def test_cpu_notebook_end_to_end(env):
    cluster, mgr = env
    cluster.add_cpu_pool("cpu", nodes=1)
    cluster.client.create(mk_notebook("mini"))

    sts = wait_for(
        lambda: cluster.client.get(StatefulSet, "user", "mini"), msg="statefulset"
    )
    assert sts.spec.replicas == 1
    assert sts.spec.template.metadata.labels[C.NOTEBOOK_NAME_LABEL] == "mini"
    tmpl_c = sts.spec.template.spec.containers[0]
    assert tmpl_c.working_dir == C.DEFAULT_WORKING_DIR
    assert tmpl_c.env_dict()[C.PREFIX_ENV] == "/notebook/user/mini"
    assert tmpl_c.ports[0].container_port == C.NOTEBOOK_PORT
    assert sts.spec.template.spec.security_context.fs_group == C.DEFAULT_FS_GROUP

    # wait_for, not a one-shot get: the reconcile creates the STS a few ms
    # before the Service in the same pass, and the STS wait above returns
    # inside exactly that gap on a loaded box
    svc = wait_for(
        lambda: cluster.client.get(Service, "user", "mini"), msg="service"
    )
    assert svc.spec.ports[0].port == 80
    assert svc.spec.ports[0].target_port == C.NOTEBOOK_PORT
    assert svc.spec.ports[0].name == C.NOTEBOOK_PORT_NAME

    nb = wait_for(
        lambda: (
            lambda n: n if n.status.ready_replicas == 1 else None
        )(cluster.client.get(Notebook, "user", "mini")),
        msg="notebook ready",
    )
    assert any(c.type == "Ready" and c.status == "True" for c in nb.status.conditions)
    assert nb.status.container_state.running is not None


def test_tpu_notebook_v5e4_milestone_a(env):
    """Milestone A: one CR on a v5e-4 pool -> slice bound, chips visible."""
    cluster, mgr = env
    cluster.add_tpu_pool("v5e-pool", "v5e", "2x2")
    cluster.client.create(mk_notebook("lab", tpu=TPUSpec(accelerator="v5e", topology="2x2")))

    sts = wait_for(lambda: cluster.client.get(StatefulSet, "user", "lab"), msg="sts")
    c = sts.spec.template.spec.containers[0]
    assert c.resources.requests[TPU_RESOURCE] == "4"
    env_d = c.env_dict()
    assert env_d["JAX_PLATFORMS"] == "tpu"
    assert env_d["TPU_ACCELERATOR_TYPE"] == "v5e-4"
    assert sts.spec.template.spec.node_selector[
        "cloud.google.com/gke-tpu-accelerator"
    ] == "tpu-v5-lite-podslice"

    nb = wait_for(
        lambda: (
            lambda n: n if n.status.tpu and n.status.tpu.mesh_ready else None
        )(cluster.client.get(Notebook, "user", "lab")),
        msg="mesh ready",
    )
    assert nb.status.tpu.hosts == 1
    assert nb.status.tpu.chips_visible == 4
    assert nb.status.tpu.chips_expected == 4


def test_tpu_multihost_v5p32(env):
    """BASELINE config: multi-host v5p-32 via headless service + ordinal env."""
    cluster, mgr = env
    cluster.add_tpu_pool("v5p-pool", "v5p", "2x2x4")
    cluster.client.create(
        mk_notebook("train", tpu=TPUSpec(accelerator="v5p", topology="2x2x4"))
    )
    sts = wait_for(lambda: cluster.client.get(StatefulSet, "user", "train"), msg="sts")
    assert sts.spec.replicas == 4
    assert sts.spec.service_name == "train-hosts"
    c = sts.spec.template.spec.containers[0]
    env_d = c.env_dict()
    assert env_d["JAX_COORDINATOR_ADDRESS"].startswith("train-0.train-hosts.user.svc")
    assert env_d["JAX_NUM_PROCESSES"] == "4"
    assert any(e.name == "TPU_WORKER_ID" and e.value_from for e in c.env)

    hosts_svc = wait_for(
        lambda: cluster.client.get(Service, "user", "train-hosts"), msg="hosts svc"
    )
    assert hosts_svc.spec.cluster_ip == "None"

    nb = wait_for(
        lambda: (
            lambda n: n if n.status.tpu and n.status.tpu.mesh_ready else None
        )(cluster.client.get(Notebook, "user", "train")),
        msg="mesh ready", timeout=90,
    )
    assert nb.status.tpu.hosts_ready == 4
    assert nb.status.tpu.chips_visible == 16
    assert nb.status.ready_replicas == 4
    # 4 pods, each on its own host in one pool
    pods = cluster.client.list(Pod, namespace="user", labels={C.NOTEBOOK_NAME_LABEL: "train"})
    assert len({p.spec.node_name for p in pods}) == 4


def test_stop_annotation_scales_to_zero(env):
    cluster, mgr = env
    cluster.add_cpu_pool("cpu", nodes=1)
    cluster.client.create(mk_notebook("s1"))
    wait_for(
        lambda: cluster.client.get(StatefulSet, "user", "s1").status.ready_replicas == 1,
        msg="ready",
    )
    cluster.client.patch(
        Notebook, "user", "s1",
        {"metadata": {"annotations": {C.STOP_ANNOTATION: "2024-01-01T00:00:00Z"}}},
    )
    wait_for(
        lambda: cluster.client.get(StatefulSet, "user", "s1").spec.replicas == 0,
        msg="scaled to 0",
    )
    wait_for(
        lambda: not cluster.client.list(Pod, namespace="user", labels={C.NOTEBOOK_NAME_LABEL: "s1"}),
        msg="pods gone",
    )
    # unstop -> comes back
    cluster.client.patch(
        Notebook, "user", "s1",
        {"metadata": {"annotations": {C.STOP_ANNOTATION: None}}},
    )
    wait_for(
        lambda: cluster.client.get(StatefulSet, "user", "s1").status.ready_replicas == 1,
        msg="restarted",
    )


def test_restart_annotation_recreates_pods(env):
    cluster, mgr = env
    cluster.add_cpu_pool("cpu", nodes=1)
    cluster.client.create(mk_notebook("r1"))
    wait_for(
        lambda: cluster.client.get(StatefulSet, "user", "r1").status.ready_replicas == 1,
        msg="ready",
    )
    uid0 = cluster.client.get(Pod, "user", "r1-0").metadata.uid
    cluster.client.patch(
        Notebook, "user", "r1",
        {"metadata": {"annotations": {C.NOTEBOOK_RESTART_ANNOTATION: "true"}}},
    )

    def recreated():
        nb = cluster.client.get(Notebook, "user", "r1")
        if C.NOTEBOOK_RESTART_ANNOTATION in nb.metadata.annotations:
            return False
        p = cluster.client.get(Pod, "user", "r1-0")
        return p.metadata.uid != uid0

    wait_for(recreated, msg="pod recreated and annotation cleared")


def test_user_spec_change_rolls_template(env):
    cluster, mgr = env
    cluster.add_cpu_pool("cpu", nodes=1)
    cluster.client.create(mk_notebook("u1", image="img:1"))
    wait_for(
        lambda: cluster.client.get(StatefulSet, "user", "u1").status.ready_replicas == 1,
        msg="ready",
    )
    nb = cluster.client.get(Notebook, "user", "u1")
    nb.spec.template.spec.containers[0].image = "img:2"
    cluster.client.update(nb)
    wait_for(
        lambda: cluster.client.get(StatefulSet, "user", "u1")
        .spec.template.spec.containers[0]
        .image
        == "img:2",
        msg="template updated",
    )
    wait_for(
        lambda: cluster.client.get(Pod, "user", "u1-0").spec.containers[0].image == "img:2",
        msg="pod recreated with new image",
    )


def test_scheduling_failure_event_mirrored_to_notebook(env):
    """No TPU pool at all -> FailedScheduling surfaces on the Notebook CR."""
    cluster, mgr = env
    cluster.client.create(
        mk_notebook("starved", tpu=TPUSpec(accelerator="v5p", topology="2x2x4"))
    )

    def mirrored():
        return [
            e
            for e in cluster.client.list(Event, namespace="user")
            if e.involved_object.kind == "Notebook"
            and e.involved_object.name == "starved"
            and e.reason == "FailedScheduling"
        ]

    events = wait_for(mirrored, msg="mirrored FailedScheduling event", timeout=15)
    assert "google.com/tpu" in events[0].message
