"""SLO-burn replica autoscaler (runtime/autoscaler.py, ISSUE 16): the pure
`decide()` policy — burn/queue-pressure scale-up, the scale-down
stabilization window as a flap damper, the minReplicas floor, scale-to-zero
parking after a genuine idle window — and the ReplicaAutoscaler sweep that
writes ONLY the desired-replicas annotation (the endpoint controller owns
every actual transition).

Deterministic tier-1 tests (marker: autoscaler); the ci/faults.sh router
lane reruns these with the router tests.
"""
from types import SimpleNamespace

import pytest

from odh_kubeflow_tpu.api.inference import (
    AutoscalingSpec,
    InferenceEndpoint,
    ServingSpec,
)
from odh_kubeflow_tpu.cluster import Client, Store
from odh_kubeflow_tpu.controllers import constants as C
from odh_kubeflow_tpu.controllers.inference import endpoint_desired_replicas
from odh_kubeflow_tpu.runtime import metrics as rm
from odh_kubeflow_tpu.runtime.autoscaler import (
    EndpointScaleState,
    ReplicaAutoscaler,
    decide,
)

pytestmark = pytest.mark.autoscaler

NS = "autoscale"


def auto(min_r=1, max_r=4, target=2.0, to_zero=False, stab=30.0, idle=120.0):
    return AutoscalingSpec(
        min_replicas=min_r, max_replicas=max_r, target_burn_rate=target,
        scale_to_zero=to_zero, scale_down_stabilization_s=stab,
        scale_to_zero_idle_s=idle,
    )


def sig(burn=0.0, queued=0.0, occupancy=0.0):
    return {"burn_rate": burn, "queue_depth": queued,
            "slot_occupancy": occupancy}


# ---------------------------------------------------------------------------
# decide(): the pure policy
# ---------------------------------------------------------------------------


def test_scale_up_on_burn_one_replica_per_tick():
    state = EndpointScaleState()
    assert decide(1, auto(), sig(burn=3.0), 0.0, state) == (2, "up")
    assert decide(2, auto(), sig(burn=3.0), 5.0, state) == (3, "up")


def test_scale_up_on_queue_pressure_without_burn():
    state = EndpointScaleState()
    assert decide(1, auto(), sig(queued=10.0), 0.0, state) == (2, "up")
    # below the pressure threshold and below target burn: hold
    assert decide(2, auto(), sig(burn=1.5, queued=3.0), 5.0,
                  EndpointScaleState()) == (2, "hold")


def test_scale_up_capped_at_max_replicas():
    state = EndpointScaleState()
    assert decide(4, auto(max_r=4), sig(burn=9.0), 0.0, state) == (4, "hold")


def test_min_replicas_floor_holds_under_sustained_low_burn():
    a = auto(min_r=2, max_r=4, stab=30.0)
    state = EndpointScaleState()
    now = 0.0
    for _ in range(20):  # hours of quiet, many stabilization windows
        desired, action = decide(2, a, sig(burn=0.0), now, state)
        assert (desired, action) == (2, "hold")
        now += 60.0


def test_scale_down_waits_for_the_stabilization_window():
    a = auto(stab=30.0)
    state = EndpointScaleState()
    assert decide(3, a, sig(burn=0.1), 0.0, state) == (3, "hold")
    assert decide(3, a, sig(burn=0.1), 29.0, state) == (3, "hold")
    assert decide(3, a, sig(burn=0.1), 31.0, state) == (2, "down")
    # one step per window: the window restarts at the down decision
    assert decide(2, a, sig(burn=0.1), 32.0, state) == (2, "hold")
    assert decide(2, a, sig(burn=0.1), 62.0, state) == (1, "down")


def test_hot_tick_resets_the_stabilization_window_flap_damped():
    a = auto(stab=30.0)
    state = EndpointScaleState()
    decide(3, a, sig(burn=0.1), 0.0, state)
    # a burn spike mid-window resets the damper (and scales up)
    assert decide(3, a, sig(burn=5.0), 20.0, state) == (4, "up")
    # low again: the 30s clock restarts from here, not from t=0
    assert decide(4, a, sig(burn=0.1), 40.0, state) == (4, "hold")
    assert decide(4, a, sig(burn=0.1), 69.0, state) == (4, "hold")
    assert decide(4, a, sig(burn=0.1), 71.0, state) == (3, "down")


def test_hysteresis_band_between_half_and_full_target_holds():
    a = auto(target=2.0, stab=10.0)
    state = EndpointScaleState()
    # burn 1.5 is below target (no up) but above target/2 (no down window)
    for now in (0.0, 20.0, 40.0):
        assert decide(3, a, sig(burn=1.5), now, state) == (3, "hold")
    assert state.below_since is None


def test_park_to_zero_only_after_the_idle_window():
    a = auto(to_zero=True, idle=120.0)
    state = EndpointScaleState()
    assert decide(1, a, sig(), 0.0, state) == (1, "hold")
    assert decide(1, a, sig(), 119.0, state) == (1, "hold")
    assert decide(1, a, sig(), 121.0, state) == (0, "park")
    # already parked: stays parked, no thrash
    assert decide(0, a, sig(), 200.0, state)[0] == 0


def test_no_park_without_scale_to_zero():
    a = auto(to_zero=False, idle=120.0)
    state = EndpointScaleState()
    for now in (0.0, 500.0, 5000.0):
        desired, action = decide(1, a, sig(), now, state)
        assert desired == 1 and action != "park"


def test_inflight_work_resets_the_idle_window():
    a = auto(to_zero=True, idle=100.0)
    state = EndpointScaleState()
    decide(1, a, sig(), 0.0, state)
    # a single queued request at t=90 means the endpoint is NOT idle
    decide(1, a, sig(queued=1.0), 90.0, state)
    assert state.idle_since is None
    assert decide(1, a, sig(), 150.0, state) == (1, "hold")
    assert decide(1, a, sig(), 251.0, state) == (0, "park")


def test_cold_wake_scales_a_parked_fleet_back_up():
    a = auto(min_r=2, to_zero=True)
    state = EndpointScaleState()
    desired, action = decide(0, a, sig(burn=3.0), 0.0, state)
    assert (desired, action) == (2, "up")  # straight to the floor


# ---------------------------------------------------------------------------
# ReplicaAutoscaler: the sweep writes only the annotation
# ---------------------------------------------------------------------------


def mk_ep(name, autoscaling=None, replicas=1):
    ep = InferenceEndpoint()
    ep.metadata.name = name
    ep.metadata.namespace = NS
    ep.spec.serving = ServingSpec(replicas=replicas, autoscaling=autoscaling)
    return ep


def mk_autoscaler(client, signals, clock, **kw):
    mgr = SimpleNamespace(client=client)
    return ReplicaAutoscaler(
        mgr, period_s=999.0, signals_fn=lambda ep: dict(signals),
        clock=lambda: clock[0], **kw,
    )


def test_tick_patches_only_the_desired_replicas_annotation():
    store = Store()
    client = Client(store)
    client.create(mk_ep("burning", autoscaling=auto(max_r=3)))
    signals = sig(burn=5.0)
    clock = [0.0]
    scaler = mk_autoscaler(client, signals, clock)
    up0 = rm.autoscaler_decisions_total.value(action="up")

    scaler.tick()
    ep = client.get(InferenceEndpoint, NS, "burning")
    assert ep.metadata.annotations[C.INFERENCE_DESIRED_REPLICAS_ANNOTATION] == "2"
    assert endpoint_desired_replicas(ep) == 2
    # the autoscaler never touches the state machine or the spec
    assert C.INFERENCE_STATE_ANNOTATION not in ep.metadata.annotations
    assert ep.spec.serving.replicas == 1
    assert rm.autoscaler_decisions_total.value(action="up") == up0 + 1
    assert rm.endpoint_desired_replicas_gauge.value(
        endpoint=f"{NS}/burning") == 2.0

    scaler.tick()  # still burning: one more replica, up to the cap
    ep = client.get(InferenceEndpoint, NS, "burning")
    assert endpoint_desired_replicas(ep) == 3
    scaler.tick()
    assert endpoint_desired_replicas(
        client.get(InferenceEndpoint, NS, "burning")) == 3  # capped


def test_tick_parks_idle_scale_to_zero_endpoint_after_window():
    store = Store()
    client = Client(store)
    client.create(mk_ep("nightly", autoscaling=auto(to_zero=True, idle=60.0)))
    signals = sig()
    clock = [0.0]
    scaler = mk_autoscaler(client, signals, clock)
    scaler.tick()
    assert endpoint_desired_replicas(
        client.get(InferenceEndpoint, NS, "nightly")) == 1
    clock[0] = 61.0
    scaler.tick()
    ep = client.get(InferenceEndpoint, NS, "nightly")
    assert ep.metadata.annotations[C.INFERENCE_DESIRED_REPLICAS_ANNOTATION] == "0"
    assert endpoint_desired_replicas(ep) == 0


def test_tick_skips_static_and_stopped_endpoints():
    store = Store()
    client = Client(store)
    client.create(mk_ep("static", autoscaling=None, replicas=2))
    stopped = mk_ep("stopped", autoscaling=auto())
    stopped.metadata.annotations[C.STOP_ANNOTATION] = "true"
    client.create(stopped)
    scaler = mk_autoscaler(client, sig(burn=9.0), [0.0])
    scaler.tick()
    for name in ("static", "stopped"):
        ep = client.get(InferenceEndpoint, NS, name)
        assert C.INFERENCE_DESIRED_REPLICAS_ANNOTATION not in \
            ep.metadata.annotations, name


def test_state_gc_for_deleted_endpoints():
    store = Store()
    client = Client(store)
    client.create(mk_ep("ghost", autoscaling=auto()))
    scaler = mk_autoscaler(client, sig(burn=0.1), [0.0])
    scaler.tick()
    assert f"{NS}/ghost" in scaler._states
    client.delete(InferenceEndpoint, NS, "ghost")
    scaler.tick()
    assert scaler._states == {}


def test_default_signals_read_serving_slos_fast_window_and_engine_gauges():
    class FakeSLOEngine:
        windows = {"fast": 300.0, "slow": 3600.0}

        def status(self):
            return {"slos": {
                "token-latency": {
                    "category": "serving",
                    "windows": {"fast": {"burn_rate": 4.5},
                                "slow": {"burn_rate": 0.2}},
                },
                "notebook-readiness": {  # wrong category: ignored
                    "category": "workload",
                    "windows": {"fast": {"burn_rate": 99.0}},
                },
            }}

    rm.global_registry.get("inference_queue_depth").set(3.0)
    rm.global_registry.get("inference_slot_occupancy_ratio").set(0.5)
    mgr = SimpleNamespace(
        client=None, slo_engine=FakeSLOEngine(), metrics=rm.global_registry,
    )
    scaler = ReplicaAutoscaler(mgr, period_s=999.0)
    signals = scaler._default_signals(mk_ep("any"))
    assert signals == {"burn_rate": 4.5, "queue_depth": 3.0,
                       "slot_occupancy": 0.5}
    rm.global_registry.get("inference_queue_depth").set(0.0)
    rm.global_registry.get("inference_slot_occupancy_ratio").set(0.0)


def test_service_lifecycle_start_stop():
    store = Store()
    client = Client(store)
    scaler = mk_autoscaler(client, sig(), [0.0])
    scaler.start()
    assert scaler._thread is not None and scaler._thread.is_alive()
    scaler.stop()
    assert scaler._thread is None
