"""Controller runtime: workqueue semantics, informer fan-out, builder wiring,
manager lifecycle, leader election."""
import threading
import time

import pytest

from odh_kubeflow_tpu.api.apps import StatefulSet
from odh_kubeflow_tpu.api.core import ConfigMap, Pod
from odh_kubeflow_tpu.api.notebook import Notebook
from odh_kubeflow_tpu.cluster import Client, Store
from odh_kubeflow_tpu.runtime import Manager, Request, Result, WorkQueue
from odh_kubeflow_tpu.runtime.manager import LeaderElector


def test_workqueue_dedup_and_singleflight():
    q = WorkQueue()
    q.add("a")
    q.add("a")
    q.add("b")
    assert len(q) == 2
    a = q.get()
    q.add(a)  # re-add while processing -> dirty, not queued
    assert len(q) == 1
    q.done(a)  # dirty -> requeued
    got = {q.get(), q.get()}
    assert got == {"a", "b"}


def test_workqueue_add_after():
    q = WorkQueue()
    t0 = time.monotonic()
    q.add_after("x", 0.15)
    assert q.get(timeout=0.05) is None
    got = q.get(timeout=2)
    assert got == "x"
    assert time.monotonic() - t0 >= 0.14


def mk_nb(name, ns="user"):
    nb = Notebook()
    nb.metadata.name = name
    nb.metadata.namespace = ns
    return nb


def test_builder_for_owns_watches():
    store = Store()
    client = Client(store)
    mgr = Manager(store)
    seen = []
    done = threading.Event()

    def reconcile(req: Request):
        seen.append(req.key)
        done.set()
        return None

    def map_pod(obj):
        name = obj.get("metadata", {}).get("labels", {}).get("notebook-name")
        if not name:
            return []
        return [(obj["metadata"].get("namespace", ""), name)]

    (
        mgr.builder("test")
        .for_(Notebook)
        .owns(StatefulSet)
        .watches(Pod, map_pod)
        .complete(reconcile)
    )
    mgr.start()
    try:
        client.create(mk_nb("alpha"))
        assert done.wait(2)
        mgr.wait_idle()
        assert "user/alpha" in seen

        # owned STS event maps back to the notebook
        seen.clear()
        nb = client.get(Notebook, "user", "alpha")
        sts = StatefulSet()
        sts.metadata.name = "alpha"
        sts.metadata.namespace = "user"
        sts.set_owner(nb)
        client.create(sts)
        mgr.wait_idle()
        assert "user/alpha" in seen

        # labeled pod maps via the custom mapper
        seen.clear()
        pod = Pod()
        pod.metadata.name = "alpha-0"
        pod.metadata.namespace = "user"
        pod.metadata.labels = {"notebook-name": "alpha"}
        client.create(pod)
        mgr.wait_idle()
        assert "user/alpha" in seen
    finally:
        mgr.stop()


def test_reconcile_error_retries_with_backoff():
    store = Store()
    client = Client(store)
    mgr = Manager(store)
    calls = []
    succeeded = threading.Event()

    def flaky(req: Request):
        calls.append(time.monotonic())
        if len(calls) < 3:
            raise RuntimeError("boom")
        succeeded.set()
        return None

    mgr.builder("flaky").for_(ConfigMap).complete(flaky)
    mgr.start()
    try:
        cm = ConfigMap()
        cm.metadata.name = "c"
        cm.metadata.namespace = "d"
        client.create(cm)
        assert succeeded.wait(5)
        assert len(calls) >= 3
    finally:
        mgr.stop()


def test_requeue_after():
    store = Store()
    client = Client(store)
    mgr = Manager(store)
    calls = []
    twice = threading.Event()

    def periodic(req: Request):
        calls.append(time.monotonic())
        if len(calls) >= 2:
            twice.set()
            return None
        return Result(requeue_after=0.1)

    mgr.builder("periodic").for_(ConfigMap).complete(periodic)
    mgr.start()
    try:
        cm = ConfigMap()
        cm.metadata.name = "p"
        cm.metadata.namespace = "d"
        client.create(cm)
        assert twice.wait(5)
        assert calls[1] - calls[0] >= 0.09
    finally:
        mgr.stop()


def test_leader_election_exclusive():
    store = Store()
    c1, c2 = Client(store), Client(store)
    e1 = LeaderElector(c1, "test-lock", identity="one", lease_duration=1.0, renew_period=0.1)
    e2 = LeaderElector(c2, "test-lock", identity="two", lease_duration=1.0, renew_period=0.1)
    e1.start()
    assert e1.is_leader.wait(2)
    e2.start()
    time.sleep(0.3)
    assert not e2.is_leader.is_set()
    # leader one dies; two takes over after the lease expires
    e1.stop()
    assert e2.is_leader.wait(5)
    e2.stop()


# ---- serving endpoints (/metrics, /healthz, /readyz) ----


def _http_get(url):
    import urllib.request

    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, resp.read().decode(), dict(resp.headers)


def test_serving_endpoints_metrics_and_health():
    """reference notebook-controller/main.go:125-133: metrics on one port,
    health pings on another; here with real liveness/readiness semantics."""
    import urllib.error

    from odh_kubeflow_tpu.api.core import ConfigMap
    from odh_kubeflow_tpu.cluster.store import Store
    from odh_kubeflow_tpu.runtime.manager import Manager
    from odh_kubeflow_tpu.runtime.metrics import Registry

    registry = Registry()
    counter = registry.counter("notebook_create_total", "Total creates")
    mgr = Manager(Store(), metrics_registry=registry)
    mgr.informers.informer_for(ConfigMap)
    server = mgr.serve_endpoints(metrics_port=0, health_port=0, host="127.0.0.1")
    try:
        mhost, mport = server.metrics_address
        hhost, hport = server.health_address

        # not started yet: alive but not ready
        status, body, _ = _http_get(f"http://{mhost}:{mport}/metrics")
        assert status == 200 and "notebook_create_total" in body
        status, body, _ = _http_get(f"http://{hhost}:{hport}/healthz")
        assert status == 200 and body == "ok\n"
        with pytest.raises(urllib.error.HTTPError) as exc:
            _http_get(f"http://{hhost}:{hport}/readyz")
        assert exc.value.code == 500

        mgr.start()
        status, body, _ = _http_get(f"http://{hhost}:{hport}/readyz")
        assert status == 200

        counter.inc()
        status, body, headers = _http_get(f"http://{mhost}:{mport}/metrics")
        assert "notebook_create_total 1" in body
        assert headers["Content-Type"].startswith("text/plain")

        with pytest.raises(urllib.error.HTTPError):
            _http_get(f"http://{hhost}:{hport}/nope")
    finally:
        server.stop()
        mgr.stop()


def test_healthz_reports_dead_controller_thread():
    from odh_kubeflow_tpu.cluster.store import Store
    from odh_kubeflow_tpu.runtime.manager import Manager

    mgr = Manager(Store())

    class DeadThread:
        def is_alive(self):
            return False

    class FakeCtrl:
        _threads = [DeadThread()]

        def start(self):
            pass

        def stop(self):
            pass

    mgr.controllers.append(FakeCtrl())
    assert mgr.healthz() is False


def test_cached_client_split_semantics():
    """controller-runtime split client: reads of WATCHED kinds serve from the
    informer cache (authoritative: miss = NotFound, no API fallthrough);
    unwatched kinds read straight through; api_reader always bypasses."""
    import pytest as _pytest

    from odh_kubeflow_tpu.api.core import ConfigMap, Service
    from odh_kubeflow_tpu.apimachinery import NotFoundError
    from odh_kubeflow_tpu.cluster.store import Store
    from odh_kubeflow_tpu.runtime.manager import Manager

    store = Store()
    mgr = Manager(store)
    inf = mgr.informers.informer_for(ConfigMap)  # ConfigMap is now "watched"
    mgr.informers.start_all()
    try:
        cm = {
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {"name": "a", "namespace": "ns", "labels": {"x": "1"}},
            "data": {"k": "v"},
        }
        store.create_raw(cm)
        deadline = time.time() + 5
        while inf.get("ns", "a") is None and time.time() < deadline:
            time.sleep(0.01)

        got = mgr.client.get(ConfigMap, "ns", "a")
        assert got.data == {"k": "v"}
        # cache-authoritative: a cache miss raises, even though the store
        # would answer (simulate lag by asking before any event could exist)
        with _pytest.raises(NotFoundError):
            mgr.client.get(ConfigMap, "ns", "nope")
        # label + namespace filtering on cached lists
        assert len(mgr.client.list(ConfigMap, namespace="ns", labels={"x": "1"})) == 1
        assert mgr.client.list(ConfigMap, namespace="other") == []
        assert mgr.client.list(ConfigMap, namespace="ns", labels={"x": "2"}) == []

        # UNWATCHED kind: falls through to the store
        svc = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": "s", "namespace": "ns"},
            "spec": {},
        }
        store.create_raw(svc)
        assert mgr.client.get(Service, "ns", "s").metadata.name == "s"
        # api_reader bypasses the cache for watched kinds too
        assert mgr.api_reader.get(ConfigMap, "ns", "a").metadata.name == "a"
    finally:
        mgr.informers.stop_all()


def test_ttl_read_client_memo_and_invalidation():
    """TTLReadClient (the webhook's read memo): 404s memoize within the TTL;
    writes — through the TTL client OR its fresh view — invalidate, so a
    helper that creates through `fresh` is never served its own stale 404."""
    import time as _time

    from odh_kubeflow_tpu.api.core import ConfigMap
    from odh_kubeflow_tpu.apimachinery import NotFoundError
    from odh_kubeflow_tpu.cluster import Store
    from odh_kubeflow_tpu.cluster.client import Client
    from odh_kubeflow_tpu.runtime.cached_client import TTLReadClient

    store = Store()
    inner = Client(store)
    calls = {"get": 0}
    real_get = inner.get

    def counting_get(cls, ns, name):
        calls["get"] += 1
        return real_get(cls, ns, name)

    inner.get = counting_get
    ttl = TTLReadClient(inner, ttl_s=30.0)

    import pytest

    with pytest.raises(NotFoundError):
        ttl.get(ConfigMap, "ns", "cm")
    with pytest.raises(NotFoundError):
        ttl.get(ConfigMap, "ns", "cm")  # memoized negative
    assert calls["get"] == 1

    # create through the FRESH view invalidates the negative entry
    cm = ConfigMap()
    cm.metadata.name = "cm"
    cm.metadata.namespace = "ns"
    cm.data = {"k": "1"}
    ttl.fresh.create(cm)
    assert ttl.get(ConfigMap, "ns", "cm").data == {"k": "1"}
    assert calls["get"] == 2

    # positive entries memoize; update through the TTL client invalidates
    ttl.get(ConfigMap, "ns", "cm")
    assert calls["get"] == 2
    cur = ttl.fresh.get(ConfigMap, "ns", "cm")
    cur.data = {"k": "2"}
    ttl.update(cur)
    assert ttl.get(ConfigMap, "ns", "cm").data == {"k": "2"}

    # list memo: second identical list is served without an inner call
    lcalls = {"n": 0}
    real_list = inner.list

    def counting_list(cls, namespace=None, labels=None):
        lcalls["n"] += 1
        return real_list(cls, namespace=namespace, labels=labels)

    inner.list = counting_list
    assert len(ttl.list(ConfigMap, namespace="ns")) == 1
    assert len(ttl.list(ConfigMap, namespace="ns")) == 1
    assert lcalls["n"] == 1
    # any write clears list memos
    ttl.delete(ConfigMap, "ns", "cm")
    assert ttl.list(ConfigMap, namespace="ns") == []
    assert lcalls["n"] == 2
