"""In-process API server semantics: optimistic concurrency, finalizers,
status subresource, admission, watches, owner-ref GC."""
import pytest

from odh_kubeflow_tpu.api.apps import StatefulSet
from odh_kubeflow_tpu.api.core import ConfigMap, Pod, Service
from odh_kubeflow_tpu.api.notebook import Notebook
from odh_kubeflow_tpu.apimachinery import (
    AdmissionDeniedError,
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
)
from odh_kubeflow_tpu.cluster import ADDED, DELETED, MODIFIED, Client, Store, retry_on_conflict


@pytest.fixture()
def client():
    return Client(Store())


def mk_cm(name, ns="default", data=None):
    cm = ConfigMap()
    cm.metadata.name = name
    cm.metadata.namespace = ns
    cm.data = data or {}
    return cm


def test_create_get_roundtrip(client):
    created = client.create(mk_cm("a", data={"k": "v"}))
    assert created.metadata.uid and created.metadata.resource_version
    got = client.get(ConfigMap, "default", "a")
    assert got.data == {"k": "v"}
    with pytest.raises(AlreadyExistsError):
        client.create(mk_cm("a"))


def test_generate_name(client):
    cm = ConfigMap()
    cm.metadata.generate_name = "nb-"
    cm.metadata.namespace = "default"
    created = client.create(cm)
    assert created.metadata.name.startswith("nb-")
    assert len(created.metadata.name) > 3


def test_update_conflict(client):
    client.create(mk_cm("a", data={"v": "1"}))
    c1 = client.get(ConfigMap, "default", "a")
    c2 = client.get(ConfigMap, "default", "a")
    c1.data["v"] = "2"
    client.update(c1)
    c2.data["v"] = "3"
    with pytest.raises(ConflictError):
        client.update(c2)

    # retry_on_conflict resolves it the way the reference does everywhere
    def attempt():
        cur = client.get(ConfigMap, "default", "a")
        cur.data["v"] = "3"
        return client.update(cur)

    out = retry_on_conflict(attempt)
    assert out.data["v"] == "3"


def test_status_subresource_isolation(client):
    sts = StatefulSet()
    sts.metadata.name = "s"
    sts.metadata.namespace = "default"
    sts.spec.replicas = 1
    client.create(sts)

    # status write doesn't clobber spec
    cur = client.get(StatefulSet, "default", "s")
    cur.status.ready_replicas = 1
    client.update_status(cur)

    # spec write doesn't clobber status
    cur = client.get(StatefulSet, "default", "s")
    assert cur.status.ready_replicas == 1
    cur.spec.replicas = 3
    cur.status.ready_replicas = 99  # must be ignored on plain update
    client.update(cur)
    final = client.get(StatefulSet, "default", "s")
    assert final.spec.replicas == 3
    assert final.status.ready_replicas == 1


def test_generation_bumps_only_on_spec_change(client):
    sts = StatefulSet()
    sts.metadata.name = "g"
    sts.metadata.namespace = "default"
    sts.spec.replicas = 1
    client.create(sts)
    cur = client.get(StatefulSet, "default", "g")
    assert cur.metadata.generation == 1
    cur.metadata.labels["x"] = "y"
    cur = client.update(cur)
    assert cur.metadata.generation == 1  # metadata-only change
    cur.spec.replicas = 2
    cur = client.update(cur)
    assert cur.metadata.generation == 2


def test_finalizer_blocks_deletion(client):
    cm = mk_cm("fin")
    cm.metadata.finalizers = ["example.com/cleanup"]
    client.create(cm)
    client.delete(ConfigMap, "default", "fin")
    # still there, terminating
    got = client.get(ConfigMap, "default", "fin")
    assert got.metadata.deletion_timestamp
    # removing the finalizer completes deletion
    got.metadata.finalizers = []
    client.update(got)
    with pytest.raises(NotFoundError):
        client.get(ConfigMap, "default", "fin")


def test_owner_gc_cascade(client):
    nb = Notebook()
    nb.metadata.name = "nb"
    nb.metadata.namespace = "user"
    nb = client.create(nb)
    sts = StatefulSet()
    sts.metadata.name = "nb"
    sts.metadata.namespace = "user"
    sts.set_owner(nb)
    client.create(sts)
    svc = Service()
    svc.metadata.name = "nb"
    svc.metadata.namespace = "user"
    svc.set_owner(nb)
    client.create(svc)

    client.delete(Notebook, "user", "nb")
    with pytest.raises(NotFoundError):
        client.get(StatefulSet, "user", "nb")
    with pytest.raises(NotFoundError):
        client.get(Service, "user", "nb")


def test_merge_patch_removes_annotation(client):
    cm = mk_cm("ann")
    cm.metadata.annotations = {"kubeflow-resource-stopped": "lock", "keep": "y"}
    client.create(cm)
    client.patch(
        ConfigMap,
        "default",
        "ann",
        {"metadata": {"annotations": {"kubeflow-resource-stopped": None}}},
    )
    got = client.get(ConfigMap, "default", "ann")
    assert "kubeflow-resource-stopped" not in got.metadata.annotations
    assert got.metadata.annotations.get("keep") == "y"


def test_watch_stream_order():
    store = Store()
    client = Client(store)
    w = store.watch("v1", "ConfigMap")
    client.create(mk_cm("w1"))
    cur = client.get(ConfigMap, "default", "w1")
    cur.data["x"] = "1"
    client.update(cur)
    client.delete(ConfigMap, "default", "w1")
    events = [w.get(timeout=1) for _ in range(3)]
    assert [e.type for e in events] == [ADDED, MODIFIED, DELETED]
    w.stop()


def test_watch_initial_state():
    store = Store()
    client = Client(store)
    client.create(mk_cm("pre"))
    w = store.watch("v1", "ConfigMap")
    ev = w.get(timeout=1)
    assert ev.type == ADDED and ev.object["metadata"]["name"] == "pre"
    w.stop()


def test_mutating_admission_runs_on_create():
    store = Store()
    client = Client(store)

    def inject_lock(req):
        if req.operation == "CREATE":
            anns = req.object.setdefault("metadata", {}).setdefault("annotations", {})
            anns["kubeflow-resource-stopped"] = "lock"
        return req.object

    store.register_webhook(
        "lock-injector", "kubeflow.org/v1beta1", "Notebook", ["CREATE"], inject_lock
    )
    nb = Notebook()
    nb.metadata.name = "nb"
    nb.metadata.namespace = "u"
    created = client.create(nb)
    assert created.metadata.annotations["kubeflow-resource-stopped"] == "lock"


def test_admission_denial_rejects_write():
    store = Store()
    client = Client(store)

    def deny(req):
        raise AdmissionDeniedError("no")

    store.register_webhook("denier", "v1", "ConfigMap", ["CREATE"], deny)
    with pytest.raises(AdmissionDeniedError):
        client.create(mk_cm("x"))
    with pytest.raises(NotFoundError):
        client.get(ConfigMap, "default", "x")


def test_spoke_version_storage_alias():
    from odh_kubeflow_tpu.cluster import register_storage_alias

    store = Store()
    register_storage_alias("kubeflow.org/v1", "Notebook", "kubeflow.org/v1beta1")
    nb_dict = {
        "apiVersion": "kubeflow.org/v1",
        "kind": "Notebook",
        "metadata": {"name": "nb", "namespace": "u"},
        "spec": {"template": {"spec": {"containers": []}}},
    }
    store.create_raw(nb_dict)
    # visible through the hub version
    got = store.get_raw("kubeflow.org/v1beta1", "Notebook", "u", "nb")
    assert got["metadata"]["name"] == "nb"
