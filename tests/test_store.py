"""In-process API server semantics: optimistic concurrency, finalizers,
status subresource, admission, watches, owner-ref GC."""
import pytest

from odh_kubeflow_tpu.api.apps import StatefulSet
from odh_kubeflow_tpu.api.core import ConfigMap, Pod, Service
from odh_kubeflow_tpu.api.notebook import Notebook
from odh_kubeflow_tpu.apimachinery import (
    AdmissionDeniedError,
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
)
from odh_kubeflow_tpu.cluster import ADDED, DELETED, MODIFIED, Client, Store, retry_on_conflict


@pytest.fixture()
def client():
    return Client(Store())


def mk_cm(name, ns="default", data=None):
    cm = ConfigMap()
    cm.metadata.name = name
    cm.metadata.namespace = ns
    cm.data = data or {}
    return cm


def test_create_get_roundtrip(client):
    created = client.create(mk_cm("a", data={"k": "v"}))
    assert created.metadata.uid and created.metadata.resource_version
    got = client.get(ConfigMap, "default", "a")
    assert got.data == {"k": "v"}
    with pytest.raises(AlreadyExistsError):
        client.create(mk_cm("a"))


def test_generate_name(client):
    cm = ConfigMap()
    cm.metadata.generate_name = "nb-"
    cm.metadata.namespace = "default"
    created = client.create(cm)
    assert created.metadata.name.startswith("nb-")
    assert len(created.metadata.name) > 3


def test_update_conflict(client):
    client.create(mk_cm("a", data={"v": "1"}))
    c1 = client.get(ConfigMap, "default", "a")
    c2 = client.get(ConfigMap, "default", "a")
    c1.data["v"] = "2"
    client.update(c1)
    c2.data["v"] = "3"
    with pytest.raises(ConflictError):
        client.update(c2)

    # retry_on_conflict resolves it the way the reference does everywhere
    def attempt():
        cur = client.get(ConfigMap, "default", "a")
        cur.data["v"] = "3"
        return client.update(cur)

    out = retry_on_conflict(attempt)
    assert out.data["v"] == "3"


def test_status_subresource_isolation(client):
    sts = StatefulSet()
    sts.metadata.name = "s"
    sts.metadata.namespace = "default"
    sts.spec.replicas = 1
    client.create(sts)

    # status write doesn't clobber spec
    cur = client.get(StatefulSet, "default", "s")
    cur.status.ready_replicas = 1
    client.update_status(cur)

    # spec write doesn't clobber status
    cur = client.get(StatefulSet, "default", "s")
    assert cur.status.ready_replicas == 1
    cur.spec.replicas = 3
    cur.status.ready_replicas = 99  # must be ignored on plain update
    client.update(cur)
    final = client.get(StatefulSet, "default", "s")
    assert final.spec.replicas == 3
    assert final.status.ready_replicas == 1


def test_generation_bumps_only_on_spec_change(client):
    sts = StatefulSet()
    sts.metadata.name = "g"
    sts.metadata.namespace = "default"
    sts.spec.replicas = 1
    client.create(sts)
    cur = client.get(StatefulSet, "default", "g")
    assert cur.metadata.generation == 1
    cur.metadata.labels["x"] = "y"
    cur = client.update(cur)
    assert cur.metadata.generation == 1  # metadata-only change
    cur.spec.replicas = 2
    cur = client.update(cur)
    assert cur.metadata.generation == 2


def test_finalizer_blocks_deletion(client):
    cm = mk_cm("fin")
    cm.metadata.finalizers = ["example.com/cleanup"]
    client.create(cm)
    client.delete(ConfigMap, "default", "fin")
    # still there, terminating
    got = client.get(ConfigMap, "default", "fin")
    assert got.metadata.deletion_timestamp
    # removing the finalizer completes deletion
    got.metadata.finalizers = []
    client.update(got)
    with pytest.raises(NotFoundError):
        client.get(ConfigMap, "default", "fin")


def test_owner_gc_cascade(client):
    nb = Notebook()
    nb.metadata.name = "nb"
    nb.metadata.namespace = "user"
    nb = client.create(nb)
    sts = StatefulSet()
    sts.metadata.name = "nb"
    sts.metadata.namespace = "user"
    sts.set_owner(nb)
    client.create(sts)
    svc = Service()
    svc.metadata.name = "nb"
    svc.metadata.namespace = "user"
    svc.set_owner(nb)
    client.create(svc)

    client.delete(Notebook, "user", "nb")
    with pytest.raises(NotFoundError):
        client.get(StatefulSet, "user", "nb")
    with pytest.raises(NotFoundError):
        client.get(Service, "user", "nb")


def test_merge_patch_removes_annotation(client):
    cm = mk_cm("ann")
    cm.metadata.annotations = {"kubeflow-resource-stopped": "lock", "keep": "y"}
    client.create(cm)
    client.patch(
        ConfigMap,
        "default",
        "ann",
        {"metadata": {"annotations": {"kubeflow-resource-stopped": None}}},
    )
    got = client.get(ConfigMap, "default", "ann")
    assert "kubeflow-resource-stopped" not in got.metadata.annotations
    assert got.metadata.annotations.get("keep") == "y"


def test_watch_stream_order():
    store = Store()
    client = Client(store)
    w = store.watch("v1", "ConfigMap")
    client.create(mk_cm("w1"))
    cur = client.get(ConfigMap, "default", "w1")
    cur.data["x"] = "1"
    client.update(cur)
    client.delete(ConfigMap, "default", "w1")
    events = [w.get(timeout=1) for _ in range(3)]
    assert [e.type for e in events] == [ADDED, MODIFIED, DELETED]
    w.stop()


def test_watch_initial_state():
    store = Store()
    client = Client(store)
    client.create(mk_cm("pre"))
    w = store.watch("v1", "ConfigMap")
    ev = w.get(timeout=1)
    assert ev.type == ADDED and ev.object["metadata"]["name"] == "pre"
    w.stop()


def test_mutating_admission_runs_on_create():
    store = Store()
    client = Client(store)

    def inject_lock(req):
        if req.operation == "CREATE":
            anns = req.object.setdefault("metadata", {}).setdefault("annotations", {})
            anns["kubeflow-resource-stopped"] = "lock"
        return req.object

    store.register_webhook(
        "lock-injector", "kubeflow.org/v1beta1", "Notebook", ["CREATE"], inject_lock
    )
    nb = Notebook()
    nb.metadata.name = "nb"
    nb.metadata.namespace = "u"
    created = client.create(nb)
    assert created.metadata.annotations["kubeflow-resource-stopped"] == "lock"


def test_admission_denial_rejects_write():
    store = Store()
    client = Client(store)

    def deny(req):
        raise AdmissionDeniedError("no")

    store.register_webhook("denier", "v1", "ConfigMap", ["CREATE"], deny)
    with pytest.raises(AdmissionDeniedError):
        client.create(mk_cm("x"))
    with pytest.raises(NotFoundError):
        client.get(ConfigMap, "default", "x")


def test_spoke_version_storage_alias():
    from odh_kubeflow_tpu.cluster import register_storage_alias

    store = Store()
    register_storage_alias("kubeflow.org/v1", "Notebook", "kubeflow.org/v1beta1")
    nb_dict = {
        "apiVersion": "kubeflow.org/v1",
        "kind": "Notebook",
        "metadata": {"name": "nb", "namespace": "u"},
        "spec": {"template": {"spec": {"containers": []}}},
    }
    store.create_raw(nb_dict)
    # visible through the hub version
    got = store.get_raw("kubeflow.org/v1beta1", "Notebook", "u", "nb")
    assert got["metadata"]["name"] == "nb"


def test_watch_resume_from_rv():
    """Watch cache: since_rv replays only events after that RV (the
    ?watch=true&resourceVersion=N resume path the HTTP transport uses)."""
    store = Store()
    a = store.create_raw(mk_cm("a").to_dict() | {"apiVersion": "v1", "kind": "ConfigMap"})
    rv_after_a = a["metadata"]["resourceVersion"]
    store.create_raw(mk_cm("b").to_dict() | {"apiVersion": "v1", "kind": "ConfigMap"})
    store.delete_raw("v1", "ConfigMap", "default", "a")

    w = store.watch("v1", "ConfigMap", since_rv=rv_after_a)
    evs = [w.get(timeout=0.2) for _ in range(2)]
    assert [e.type for e in evs] == [ADDED, DELETED]
    assert evs[0].object["metadata"]["name"] == "b"
    assert evs[1].object["metadata"]["name"] == "a"
    # resume cursor: the DELETED event carries a fresh RV past rv_after_a
    assert int(evs[1].object["metadata"]["resourceVersion"]) > int(rv_after_a)
    # live events still flow after the replay
    store.create_raw(mk_cm("c").to_dict() | {"apiVersion": "v1", "kind": "ConfigMap"})
    ev = w.get(timeout=1)
    assert ev.type == ADDED and ev.object["metadata"]["name"] == "c"
    w.stop()


def test_watch_resume_namespace_filtered():
    store = Store()
    rv0 = store.current_rv()
    store.create_raw(mk_cm("a", ns="one").to_dict() | {"apiVersion": "v1", "kind": "ConfigMap"})
    store.create_raw(mk_cm("b", ns="two").to_dict() | {"apiVersion": "v1", "kind": "ConfigMap"})
    w = store.watch("v1", "ConfigMap", namespace="two", since_rv=rv0)
    ev = w.get(timeout=0.2)
    assert ev.object["metadata"]["name"] == "b"
    assert w.get(timeout=0.05) is None
    w.stop()


def test_watch_resume_too_old_is_gone():
    from odh_kubeflow_tpu.apimachinery import GoneError

    store = Store(watch_history_limit=4)
    for i in range(8):
        store.create_raw(
            mk_cm(f"cm-{i}").to_dict() | {"apiVersion": "v1", "kind": "ConfigMap"}
        )
    with pytest.raises(GoneError):
        store.watch("v1", "ConfigMap", since_rv="1")


def test_current_rv_tracks_writes():
    store = Store()
    before = int(store.current_rv())
    store.create_raw(mk_cm("x").to_dict() | {"apiVersion": "v1", "kind": "ConfigMap"})
    assert int(store.current_rv()) > before


def test_list_raw_with_rv_atomic_snapshot():
    store = Store()
    store.create_raw(mk_cm("a").to_dict() | {"apiVersion": "v1", "kind": "ConfigMap"})
    items, rv = store.list_raw_with_rv("v1", "ConfigMap")
    assert [o["metadata"]["name"] for o in items] == ["a"]
    # a watch resumed from the snapshot RV sees exactly the post-snapshot write
    store.create_raw(mk_cm("b").to_dict() | {"apiVersion": "v1", "kind": "ConfigMap"})
    w = store.watch("v1", "ConfigMap", since_rv=rv)
    ev = w.get(timeout=0.2)
    assert ev.type == ADDED and ev.object["metadata"]["name"] == "b"
    w.stop()
