"""Native storage core: backend parity, ctypes binding, fallback."""
import json

import pytest

from odh_kubeflow_tpu._native import ensure_built, load
from odh_kubeflow_tpu.cluster.store import Store

HAVE_NATIVE = ensure_built() and load() is not None

needs_native = pytest.mark.skipif(not HAVE_NATIVE, reason="libnbstore.so unavailable")


def _lifecycle(store: Store) -> list:
    """One scripted CRUD+finalizer+GC sequence; returns observable states."""
    out = []
    owner = store.create_raw(
        {
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {"name": "own", "namespace": "ns", "finalizers": ["keep"]},
            "data": {"k": "v"},
        }
    )
    child = store.create_raw(
        {
            "apiVersion": "v1",
            "kind": "Secret",
            "metadata": {
                "name": "dep",
                "namespace": "ns",
                "ownerReferences": [
                    {"apiVersion": "v1", "kind": "ConfigMap", "name": "own",
                     "uid": owner["metadata"]["uid"]}
                ],
            },
        }
    )
    out.append(("rv_distinct", owner["metadata"]["resourceVersion"]
                != child["metadata"]["resourceVersion"]))
    got = store.get_raw("v1", "ConfigMap", "ns", "own")
    got["data"]["k"] = "v2"
    updated = store.update_raw(got)
    out.append(("update_data", updated["data"]["k"]))
    # snapshot isolation: mutating a returned object must not touch the store
    updated["data"]["k"] = "corrupted"
    out.append(("isolated", store.get_raw("v1", "ConfigMap", "ns", "own")["data"]["k"]))
    store.delete_raw("v1", "ConfigMap", "ns", "own")
    pending = store.get_raw("v1", "ConfigMap", "ns", "own")
    out.append(("deletion_pending", bool(pending["metadata"].get("deletionTimestamp"))))
    pending["metadata"]["finalizers"] = []
    store.update_raw(pending)
    out.append(("owner_gone", "own" not in [
        o["metadata"]["name"] for o in store.list_raw("v1", "ConfigMap", namespace="ns")
    ]))
    out.append(("child_gced", store.list_raw("v1", "Secret", namespace="ns") == []))
    return out


@needs_native
def test_native_backend_selected_by_default():
    assert Store().backend == "native"


def test_python_backend_forced():
    assert Store(backend="python").backend == "python"


def test_unknown_backend_rejected():
    with pytest.raises(ValueError):
        Store(backend="etcd")


@pytest.mark.parametrize("backend", ["python"] + (["native"] if HAVE_NATIVE else []))
def test_non_json_object_rejected_cleanly(backend):
    """Canonical-JSON contract: sets/NaN raise InvalidError (never a bare
    TypeError mid-write); non-string keys coerce to strings, as JSON does."""
    from odh_kubeflow_tpu.apimachinery import InvalidError

    store = Store(backend=backend)
    for bad in [{"when": {1, 2}}, {"n": float("nan")}]:
        with pytest.raises(InvalidError):
            store.create_raw(
                {
                    "apiVersion": "v1",
                    "kind": "ConfigMap",
                    "metadata": {"name": "bad", "namespace": "ns"},
                    "data": bad,
                }
            )
    created = store.create_raw(
        {
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {"name": "coerced", "namespace": "ns"},
            "data": {1: "x"},
        }
    )
    assert created["data"] == {"1": "x"}


@needs_native
def test_backend_parity_full_lifecycle():
    assert _lifecycle(Store(backend="native")) == _lifecycle(Store(backend="python"))


def test_python_lifecycle_semantics():
    states = dict(_lifecycle(Store(backend="python")))
    assert states == {
        "rv_distinct": True,
        "update_data": "v2",
        "isolated": "v2",
        "deletion_pending": True,
        "owner_gone": True,
        "child_gced": True,
    }


@needs_native
def test_native_store_raw_binding():
    from odh_kubeflow_tpu._native import NativeStore

    s = NativeStore()
    assert s.next_rv() == 1
    payload = json.dumps({"big": "x" * 10000}).encode()
    s.put("b", "k", payload)
    assert s.get("b", "k") == payload
    assert s.list("b") == [payload]
    assert s.pop("b", "k") == payload
    assert s.get("b", "k") is None
    assert s.count("b") == 0


@needs_native
def test_native_list_is_key_ordered():
    from odh_kubeflow_tpu._native import NativeStore

    s = NativeStore()
    for name in ["zz", "aa", "mm"]:
        s.put("b", name, json.dumps({"n": name}).encode())
    assert [json.loads(r)["n"] for r in s.list("b")] == ["aa", "mm", "zz"]


def _seed_labeled(store, n=60):
    for i in range(n):
        store.create_raw(
            {
                "apiVersion": "v1",
                "kind": "ConfigMap",
                "metadata": {
                    "name": f"cm-{i:03d}",
                    "namespace": f"ns-{i % 3}",
                    "labels": {"app": f"app-{i % 5}", "tier": "web" if i % 2 else "db"},
                },
                "data": {"i": str(i)},
            }
        )


@needs_native
def test_filtered_list_parity_with_python_backend():
    native, python = Store(backend="native"), Store(backend="python")
    for s in (native, python):
        _seed_labeled(s)
    cases = [
        dict(namespace=None, label_selector=None),
        dict(namespace="ns-1", label_selector=None),
        dict(namespace=None, label_selector={"app": "app-2"}),
        dict(namespace="ns-0", label_selector={"app": "app-0", "tier": "db"}),
        dict(namespace="nope", label_selector=None),
        dict(namespace=None, label_selector={"app": "missing"}),
    ]
    def ident(objs):
        return [
            (o["metadata"]["namespace"], o["metadata"]["name"], o["data"])
            for o in objs
        ]

    for kw in cases:
        a = native.list_raw("v1", "ConfigMap", **kw)
        b = python.list_raw("v1", "ConfigMap", **kw)
        assert ident(a) == ident(b), kw
    assert len(native.list_raw("v1", "ConfigMap", namespace="ns-1")) == 20


@needs_native
def test_filtered_list_handles_separator_chars_in_labels():
    """The \\x1e/\\x1f encoding must stay exact for hostile label text."""
    native, python = Store(backend="native"), Store(backend="python")
    weird = {"k": "a\x1fb", "k2": "c\x1ed", "k3": "back\\slash"}
    for s in (native, python):
        s.create_raw(
            {
                "apiVersion": "v1",
                "kind": "ConfigMap",
                "metadata": {"name": "w", "namespace": "ns", "labels": dict(weird)},
            }
        )
        s.create_raw(
            {
                "apiVersion": "v1",
                "kind": "ConfigMap",
                # label value that would collide if escaping were not injective
                "metadata": {"name": "x", "namespace": "ns",
                             "labels": {"k": "a", "fake": "b"}},
            }
        )
    for sel in [dict(weird), {"k": "a\x1fb"}, {"k": "a"}, {"k": "a", "fake": "b"}]:
        a = native.list_raw("v1", "ConfigMap", label_selector=sel)
        b = python.list_raw("v1", "ConfigMap", label_selector=sel)
        assert [o["metadata"]["name"] for o in a] == [
            o["metadata"]["name"] for o in b
        ], sel


@needs_native
def test_native_store_throughput_exceeds_python(capsys):
    """Informational microbench (no hard assert — CI machines vary)."""
    import time

    def bench(store):
        t0 = time.perf_counter()
        n = 300
        for i in range(n):
            store.create_raw(
                {
                    "apiVersion": "v1",
                    "kind": "ConfigMap",
                    "metadata": {"name": f"cm-{i}", "namespace": "ns"},
                    "data": {"payload": "x" * 256},
                }
            )
        for i in range(n):
            obj = store.get_raw("v1", "ConfigMap", "ns", f"cm-{i}")
            obj["data"]["payload"] = "y" * 256
            store.update_raw(obj)
        store.list_raw("v1", "ConfigMap", namespace="ns")
        return time.perf_counter() - t0

    t_native = bench(Store(backend="native"))
    t_python = bench(Store(backend="python"))

    def bench_selective_list(store):
        import time

        for ns in range(20):
            for i in range(50):
                store.create_raw(
                    {
                        "apiVersion": "v1",
                        "kind": "Secret",
                        "metadata": {"name": f"s-{i}", "namespace": f"ns-{ns}",
                                     "labels": {"notebook-name": f"nb-{i}"}},
                        "data": {"blob": "z" * 2048},
                    }
                )
        t0 = time.perf_counter()
        for _ in range(50):
            out = store.list_raw(
                "v1", "Secret", namespace="ns-7",
                label_selector={"notebook-name": "nb-3"},
            )
            assert len(out) == 1
        return time.perf_counter() - t0

    tl_native = bench_selective_list(Store(backend="native"))
    tl_python = bench_selective_list(Store(backend="python"))
    with capsys.disabled():
        print(
            f"\n[native-store bench] crud: native={t_native:.3f}s "
            f"python={t_python:.3f}s | selective list x50 over 1000 objs: "
            f"native={tl_native:.3f}s python={tl_python:.3f}s "
            f"({tl_python / max(tl_native, 1e-9):.1f}x)"
        )
    # regression gates (VERDICT r3 weak #8): the mirror keeps point CRUD at
    # python-backend speed (generous 2x bound for noisy CI boxes), and the
    # native filtered list must stay an order of magnitude ahead
    assert t_native < 2.0 * t_python, (t_native, t_python)
    assert tl_native * 10 < tl_python, (tl_native, tl_python)
