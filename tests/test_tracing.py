"""End-to-end readiness tracing (ISSUE 2 tentpole): W3C traceparent
primitives, cross-component propagation through the sim (webhook ->
reconciler phases -> kubelet -> probe gate -> jax.devices.ready), the
/debug/traces endpoint, structured JSON logs with trace correlation, and the
calm-path overhead bound."""
from __future__ import annotations

import json
import logging
import threading
import time

import pytest

from odh_kubeflow_tpu.utils import tracing

pytestmark = pytest.mark.observability


@pytest.fixture(autouse=True)
def _clean_traces():
    tracing.set_enabled(True)
    tracing.clear()
    yield
    tracing.set_enabled(True)


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------


def test_traceparent_roundtrip():
    trace_id, span_id = tracing.new_trace_id(), tracing.new_span_id()
    header = tracing.format_traceparent(trace_id, span_id)
    assert tracing.parse_traceparent(header) == (trace_id, span_id)


@pytest.mark.parametrize(
    "bad",
    [
        None,
        "",
        "garbage",
        "00-short-short-01",
        "00-" + "0" * 32 + "-" + "1" * 16 + "-01",  # all-zero trace id
        "00-" + "1" * 32 + "-" + "0" * 16 + "-01",  # all-zero span id
        "00-" + "z" * 32 + "-" + "1" * 16 + "-01",  # non-hex
    ],
)
def test_traceparent_rejects_malformed(bad):
    assert tracing.parse_traceparent(bad) is None


def test_span_nesting_and_remote_parent():
    tracer = tracing.Tracer("t")
    with tracer.start_span("parent") as parent:
        assert tracing.current_traceparent() == parent.traceparent
        with tracer.start_span("child") as child:
            assert child.trace_id == parent.trace_id
            assert child.parent_id == parent.span_id
    # explicit traceparent (the annotation/header path) overrides ambient
    header = tracing.format_traceparent("ab" * 16, "cd" * 8)
    with tracer.start_span("remote-child", traceparent=header) as span:
        assert span.trace_id == "ab" * 16
        assert span.parent_id == "cd" * 8
    names = [s["name"] for s in tracing.recent_spans()]
    assert names == ["child", "parent", "remote-child"]  # completion order


def test_attach_adopts_header_without_recording():
    tracer = tracing.Tracer("t")
    header = tracing.format_traceparent("ef" * 16, "12" * 8)
    with tracing.attach(header):
        assert tracing.current_traceparent() == header
        with tracer.start_span("inside") as span:
            assert span.trace_id == "ef" * 16
    assert [s["name"] for s in tracing.recent_spans()] == ["inside"]


def test_disabled_records_nothing():
    tracing.set_enabled(False)
    tracer = tracing.Tracer("t")
    with tracer.start_span("off") as span:
        span.set_attribute("k", "v")  # must not raise on the no-op span
    assert tracing.begin_root("off-root") is None
    assert tracing.record_span("off-oneshot") is None
    assert tracing.recent_spans() == []


def test_root_dedup_by_key():
    """Re-opening a root under the same key (a retried CREATE whose earlier
    attempt failed after admission) replaces the stale root instead of
    stranding it."""
    first = tracing.begin_root("notebook.ready", key="ns/nb")
    second = tracing.begin_root("notebook.ready", key="ns/nb")
    assert tracing.open_root(first.trace_id) is None  # stale one dropped
    assert tracing.open_root(second.trace_id) is second
    assert tracing.finish_root(second.trace_id) is second
    assert tracing._open_roots == {} and tracing._root_id_by_key == {}


def test_root_lifecycle_and_discard():
    root = tracing.begin_root("root", who="test")
    assert tracing.open_root(root.trace_id) is root
    done = tracing.finish_root(root.trace_id, chips=4)
    assert done is root and done.attributes["chips"] == 4
    assert tracing.finish_root(root.trace_id) is None  # once only
    orphan = tracing.begin_root("orphan")
    tracing.discard_root(orphan.trace_id)
    names = [s["name"] for s in tracing.recent_spans()]
    assert names == ["root"]  # the discarded root never exported


def test_root_registry_metrics_track_active_and_evicted():
    """ISSUE 5 satellite: the root registry's population and every drop
    reason are visible on /metrics — a leak shows up as a climbing gauge,
    not a silent capacity eviction."""
    from odh_kubeflow_tpu.runtime.metrics import (
        tracing_roots_active,
        tracing_roots_evicted_total,
    )

    deleted0 = tracing_roots_evicted_total.value(reason="deleted")
    reopened0 = tracing_roots_evicted_total.value(reason="reopened")
    a = tracing.begin_root("notebook.ready", key="obs/leak-a")
    tracing.begin_root("notebook.ready", key="obs/leak-b")
    assert tracing_roots_active.value() == 2

    # close-on-delete: the reconciler's path for a deleted CR
    dropped = tracing.discard_root_for("obs/leak-a")
    assert dropped is a
    assert tracing_roots_active.value() == 1
    assert tracing_roots_evicted_total.value(reason="deleted") == deleted0 + 1
    assert tracing.discard_root_for("obs/leak-a") is None  # idempotent
    assert tracing_roots_evicted_total.value(reason="deleted") == deleted0 + 1
    # a dropped root is never exported as a span
    assert tracing.recent_spans(name="notebook.ready") == []

    # stale re-open under the same key counts as an eviction too
    tracing.begin_root("notebook.ready", key="obs/leak-b")
    assert tracing_roots_evicted_total.value(reason="reopened") == reopened0 + 1
    assert tracing_roots_active.value() == 1


def test_notebook_delete_closes_open_root():
    """A notebook deleted before it ever reaches ready must close its
    readiness root deterministically (the reconciler calls
    discard_root_for), not leak it until capacity eviction."""
    from odh_kubeflow_tpu.api.core import Container
    from odh_kubeflow_tpu.api.notebook import Notebook
    from odh_kubeflow_tpu.cluster import SimCluster
    from odh_kubeflow_tpu.controllers import Config
    from odh_kubeflow_tpu.main import build_manager

    cluster = SimCluster().start()
    # deliberately NO node pool: the notebook can never schedule, so the
    # root can only close via the delete path under test
    mgr = build_manager(cluster.store, Config(slo_enabled=False))
    mgr.start()
    try:
        nb = Notebook()
        nb.metadata.name = "doomed"
        nb.metadata.namespace = "obs"
        nb.spec.template.spec.containers = [Container(name="doomed", image="i")]
        cluster.client.create(nb)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if tracing._root_id_by_key.get("obs/doomed"):
                break
            time.sleep(0.02)
        assert tracing._root_id_by_key.get("obs/doomed"), "webhook opened no root"

        cluster.client.delete(Notebook, "obs", "doomed")
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if "obs/doomed" not in tracing._root_id_by_key:
                break
            time.sleep(0.02)
        assert "obs/doomed" not in tracing._root_id_by_key, (
            "deleting the notebook must close its open readiness root"
        )
    finally:
        mgr.stop()
        cluster.stop()


# ---------------------------------------------------------------------------
# the connected readiness trace (acceptance criterion)
# ---------------------------------------------------------------------------


def _ready_notebook(cluster, name="nb-trace", namespace="obs", timeout=30.0):
    from odh_kubeflow_tpu.api.core import Container
    from odh_kubeflow_tpu.api.notebook import Notebook, TPUSpec

    nb = Notebook()
    nb.metadata.name = name
    nb.metadata.namespace = namespace
    nb.spec.template.spec.containers = [Container(name=name, image="jupyter:latest")]
    nb.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2")
    cluster.client.create(nb)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        cur = cluster.client.get(Notebook, namespace, name)
        if cur.status.tpu and cur.status.tpu.mesh_ready:
            return cur
        time.sleep(0.02)
    raise AssertionError(f"{namespace}/{name} never mesh-ready")


def test_connected_readiness_trace_and_debug_endpoint():
    """One sim run yields ONE connected trace: root `notebook.ready` covers
    CR-submit -> jax.devices.ready and carries webhook / reconcile-phase /
    kubelet / probe children; /debug/traces serves it as JSON."""
    import urllib.request

    from odh_kubeflow_tpu.cluster import SimCluster
    from odh_kubeflow_tpu.controllers import Config
    from odh_kubeflow_tpu.controllers import constants as C
    from odh_kubeflow_tpu.main import build_manager
    from odh_kubeflow_tpu.probe import sim_agent_behavior

    cluster = SimCluster().start()
    agents: dict = {}
    cluster.add_pod_behavior(sim_agent_behavior(agents, duty=0.9))
    cluster.add_tpu_pool("v5e", "v5e", "2x2", slices=1)
    mgr = build_manager(
        cluster.store, Config(readiness_probe_period_s=0.1), http_get=cluster.http_get
    )
    mgr.start()
    endpoints = mgr.serve_endpoints(metrics_port=0, health_port=0, host="127.0.0.1")
    try:
        nb = _ready_notebook(cluster)
        header = nb.metadata.annotations.get(C.TRACEPARENT_ANNOTATION)
        ctx = tracing.parse_traceparent(header)
        assert ctx is not None, "webhook must stamp a valid traceparent at CREATE"
        trace_id, root_span_id = ctx

        # the mesh_ready status write lands a beat BEFORE the probe
        # controller records the terminal spans — wait for the root to close
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if tracing.recent_spans(trace_id=trace_id, name="notebook.ready"):
                break
            time.sleep(0.02)

        spans = tracing.recent_spans(trace_id=trace_id)
        by_name = {}
        for s in spans:
            by_name.setdefault(s["name"], []).append(s)
        for phase in (
            "webhook.mutate",
            "reconcile.notebook",
            "reconcile.statefulset",
            "reconcile.service",
            "reconcile.route",
            "kubelet.container.start",
            "probe.first_healthy",
            "jax.devices.ready",
            "notebook.ready",
        ):
            assert phase in by_name, f"missing phase span {phase}"
        (root,) = by_name["notebook.ready"]
        assert root.get("span_id") == root_span_id
        # the root envelope covers the bring-up (FIRST) occurrence of every
        # phase; steady-state re-reconciles after mesh-ready may outlive it
        for name, group in by_name.items():
            if name == "notebook.ready":
                continue
            first = min(group, key=lambda s: s["start_time"])
            assert first["start_time"] >= root["start_time"] - 0.001, name
            assert first["end_time"] <= root["end_time"] + 0.001, name
        # direct children hang off the root span id
        assert by_name["webhook.mutate"][0]["parent_id"] == root_span_id
        assert by_name["kubelet.container.start"][0]["parent_id"] == root_span_id

        # /debug/traces serves the same spans over HTTP, filterable by trace
        host, port = endpoints.metrics_address
        with urllib.request.urlopen(
            f"http://{host}:{port}/debug/traces?trace_id={trace_id}", timeout=5
        ) as resp:
            payload = json.loads(resp.read())
        served = {s["name"] for s in payload["spans"]}
        assert "notebook.ready" in served and "kubelet.container.start" in served
        assert all(s["trace_id"] == trace_id for s in payload["spans"])
        # /healthz rides the same mux
        with urllib.request.urlopen(
            f"http://{host}:{port}/healthz", timeout=5
        ) as resp:
            assert resp.status == 200
    finally:
        endpoints.stop()
        mgr.stop()
        cluster.stop()


def test_bench_phase_breakdown_reports_all_phases():
    """bench.py's breakdown helper decomposes the trace buffer into per-phase
    p50s (the artifact consumers read)."""
    import bench

    from odh_kubeflow_tpu.cluster import SimCluster
    from odh_kubeflow_tpu.controllers import Config
    from odh_kubeflow_tpu.main import build_manager
    from odh_kubeflow_tpu.probe import sim_agent_behavior

    cluster = SimCluster().start()
    agents: dict = {}
    cluster.add_pod_behavior(sim_agent_behavior(agents, duty=0.9))
    cluster.add_tpu_pool("v5e", "v5e", "2x2", slices=1)
    mgr = build_manager(
        cluster.store, Config(readiness_probe_period_s=0.1), http_get=cluster.http_get
    )
    mgr.start()
    try:
        _ready_notebook(cluster, name="nb-bench")
        # see test_connected_readiness_trace: wait out the write-to-span gap
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            breakdown = bench._readiness_phase_breakdown()
            if "notebook.ready" in breakdown:
                break
            time.sleep(0.02)
    finally:
        mgr.stop()
        cluster.stop()
    for phase in ("notebook.ready", "webhook.mutate", "kubelet.container.start",
                  "probe.first_healthy"):
        assert phase in breakdown, phase
        assert breakdown[phase]["p50_ms"] >= 0
        assert breakdown[phase]["traces"] >= 1


def test_webhook_denial_discards_root():
    """A denied CREATE must not leak an open root span."""
    from odh_kubeflow_tpu.cluster import Client, Store
    from odh_kubeflow_tpu.api.core import Container
    from odh_kubeflow_tpu.api.notebook import Notebook, TPUSpec
    from odh_kubeflow_tpu.apimachinery import AdmissionDeniedError
    from odh_kubeflow_tpu.controllers.webhook import NotebookWebhook

    store = Store()
    client = Client(store)
    NotebookWebhook(client).register(store)
    nb = Notebook()
    nb.metadata.name = "bad-tpu"
    nb.metadata.namespace = "obs"
    nb.spec.template.spec.containers = [Container(name="bad-tpu", image="i")]
    nb.spec.tpu = TPUSpec(accelerator="v5e", topology="9x9x9")
    with pytest.raises(AdmissionDeniedError):
        client.create(nb)
    assert tracing._open_roots == {}
    assert tracing.recent_spans(name="notebook.ready") == []


# ---------------------------------------------------------------------------
# structured JSON logs
# ---------------------------------------------------------------------------


def test_json_log_formatter_injects_trace_and_identity():
    from odh_kubeflow_tpu.utils.logging import JSONLogFormatter, log_context

    formatter = JSONLogFormatter()
    logger = logging.getLogger("obs-test")
    tracer = tracing.Tracer("t")
    with log_context(controller="notebook", namespace="obs", name="nb-1"):
        with tracer.start_span("logged") as span:
            record = logger.makeRecord(
                "obs-test", logging.INFO, __file__, 1, "hello %s", ("world",), None
            )
            line = formatter.format(record)
    out = json.loads(line)
    assert out["message"] == "hello world"
    assert out["level"] == "INFO"
    assert out["controller"] == "notebook"
    assert out["namespace"] == "obs" and out["name"] == "nb-1"
    assert out["trace_id"] == span.trace_id
    assert out["span_id"] == span.span_id
    assert out["ts"].endswith("Z")


def test_log_context_nests_and_restores():
    from odh_kubeflow_tpu.utils.logging import current_log_context, log_context

    with log_context(controller="a"):
        with log_context(namespace="b"):
            assert current_log_context() == {"controller": "a", "namespace": "b"}
        assert current_log_context() == {"controller": "a"}
    assert current_log_context() == {}


def test_reconcile_logs_carry_identity():
    """The controller worker binds controller/namespace/name around the
    reconciler, so any record logged inside carries the identity."""
    from odh_kubeflow_tpu.runtime.controller import Controller
    from odh_kubeflow_tpu.utils.logging import current_log_context

    seen = {}
    done = threading.Event()

    def reconciler(req):
        seen.update(current_log_context())
        done.set()
        return None

    ctrl = Controller("obs-ctl", reconciler)
    ctrl.start()
    try:
        ctrl.enqueue("obs", "nb-7")
        assert done.wait(5)
    finally:
        ctrl.stop()
    assert seen == {"controller": "obs-ctl", "namespace": "obs", "name": "nb-7"}


# ---------------------------------------------------------------------------
# calm-path overhead (tier-1 bound)
# ---------------------------------------------------------------------------


def _reconcile_loop_wall(n: int) -> float:
    """Wall-clock for n single-worker reconciles of a traced no-op reconciler
    through the full Controller/WorkQueue machinery."""
    from odh_kubeflow_tpu.runtime.controller import Controller
    from odh_kubeflow_tpu.utils.tracing import reconcile_tracer

    count = [0]
    done = threading.Event()

    def reconciler(req):
        with reconcile_tracer.start_span("overhead.reconcile"):
            pass
        count[0] += 1
        if count[0] >= n:
            done.set()
        return None

    ctrl = Controller("overhead", reconciler)
    ctrl.start()
    try:
        t0 = time.perf_counter()
        for i in range(n):
            ctrl.enqueue("obs", f"nb-{i}")
        assert done.wait(60)
        return time.perf_counter() - t0
    finally:
        ctrl.stop()


def test_tracing_overhead_negligible_on_calm_path():
    """Tracing + metrics must not tax the calm path: the added wall-clock per
    reconcile with tracing ENABLED vs DISABLED stays under 2 ms (generous —
    measured sub-50us; the bound only catches pathological regressions like
    lock contention or per-span I/O)."""
    n = 300
    _reconcile_loop_wall(50)  # warm imports/threads before measuring
    tracing.set_enabled(False)
    t_disabled = min(_reconcile_loop_wall(n) for _ in range(2))
    tracing.set_enabled(True)
    t_enabled = min(_reconcile_loop_wall(n) for _ in range(2))
    added_per_reconcile = max(0.0, t_enabled - t_disabled) / n
    assert added_per_reconcile < 0.002, (
        f"tracing adds {added_per_reconcile * 1e3:.3f} ms per reconcile "
        f"(enabled {t_enabled:.3f}s vs disabled {t_disabled:.3f}s over {n})"
    )
