"""Mixture-of-Experts + expert parallelism (models/moe.py, `ep` mesh axis)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from odh_kubeflow_tpu.models import (
    MoEConfig,
    TransformerConfig,
    forward,
    init_params,
    loss_fn,
    make_train_step,
    param_specs,
)
from odh_kubeflow_tpu.models.moe import init_moe_params, moe_ffn, route_topk
from odh_kubeflow_tpu.parallel import MeshPlan, shard_batch


def test_route_topk_invariants():
    """Dispatch entries are one-hot; combine weights per token sum to 1
    (when capacity admits); oversubscription drops instead of overflowing."""
    rng = jax.random.PRNGKey(0)
    n, e, k = 32, 4, 2
    logits = jax.random.normal(rng, (n, e))
    capacity = 16
    dispatch, combine, aux = route_topk(logits, k, capacity)
    assert dispatch.shape == (n, e, capacity)
    # each token occupies at most k slots, each slot at most one token
    assert float(jnp.max(jnp.sum(dispatch, axis=(1, 2)))) <= k
    assert float(jnp.max(jnp.sum(dispatch, axis=0))) <= 1.0 + 1e-6
    # combine weights normalized per token
    sums = jnp.sum(combine, axis=(1, 2))
    assert np.allclose(sums[sums > 0], 1.0, atol=1e-5)
    assert float(aux) > 0

    # capacity 1: per-expert buffer holds exactly one token
    d1, c1, _ = route_topk(logits, k, 1)
    assert float(jnp.max(jnp.sum(d1, axis=0))) <= 1.0 + 1e-6


def test_moe_ffn_matches_dense_expert_on_uniform_routing():
    """With a single expert, MoE must reduce to that expert's SwiGLU."""
    rng = jax.random.PRNGKey(1)
    d, f = 64, 128
    cfg = MoEConfig(n_experts=1, experts_per_token=1, capacity_factor=2.0, d_ff=f)
    params = init_moe_params(rng, d, cfg, jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 8, d))
    out, aux = moe_ffn(x, params, cfg)
    w_gate, w_up, w_out = (
        params["we_gate"][0],
        params["we_up"][0],
        params["we_out"][0],
    )
    expected = (jax.nn.silu(x @ w_gate) * (x @ w_up)) @ w_out
    assert jnp.allclose(out, expected, atol=1e-4, rtol=1e-4)
    assert aux.shape == ()


def test_moe_transformer_forward_and_loss():
    cfg = TransformerConfig(
        vocab=128,
        d_model=64,
        n_layers=2,
        n_heads=2,
        d_ff=128,
        dtype=jnp.float32,
        use_flash=False,
        moe=MoEConfig(n_experts=4, experts_per_token=2),
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    assert "we_gate" in params["layers"] and "wi_gate" not in params["layers"]
    assert params["layers"]["we_gate"].shape == (2, 4, 64, 128)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab)
    logits, aux = forward(params, tokens, cfg, with_aux=True)
    assert logits.shape == (2, 32, 128)
    assert float(aux) > 0  # router aux accumulated over layers
    loss = loss_fn(params, {"tokens": tokens}, cfg)
    assert jnp.isfinite(loss)


def test_moe_gradients_flow_to_experts_and_router():
    cfg = TransformerConfig(
        vocab=64,
        d_model=32,
        n_layers=1,
        n_heads=2,
        d_ff=64,
        dtype=jnp.float32,
        use_flash=False,
        remat=False,
        moe=MoEConfig(n_experts=2, experts_per_token=2, capacity_factor=4.0),
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab)
    grads = jax.grad(loss_fn)(params, {"tokens": tokens}, cfg)
    assert float(jnp.sum(jnp.abs(grads["layers"]["we_gate"]))) > 0
    assert float(jnp.sum(jnp.abs(grads["layers"]["router"]))) > 0


def test_moe_expert_parallel_train_step_on_mesh():
    """Full sharded MoE train step over an 8-device mesh with a live `ep`
    axis: expert weights sharded over ep, dispatch/combine all-to-alls
    inserted by XLA, loss finite and deterministic vs the unsharded run."""
    from jax.sharding import NamedSharding

    plan = MeshPlan.auto(8, want_ep=2, want_tp=2, want_sp=2)
    assert plan.ep == 2
    mesh = plan.build(jax.devices()[:8])
    cfg = TransformerConfig(
        vocab=128,
        d_model=64,
        n_layers=2,
        n_heads=4,
        d_ff=128,
        dtype=jnp.float32,
        use_flash=False,
        seq_axis="sp" if plan.sp > 1 else "",
        moe=MoEConfig(n_experts=4, experts_per_token=2, capacity_factor=2.0),
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    specs = param_specs(cfg, mesh)
    assert "we_gate" in specs["layers"]
    sharded = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs
    )
    # expert dim genuinely sharded over ep
    ws = sharded["layers"]["we_gate"]
    assert ws.sharding.spec[1] == "ep"

    step, opt = make_train_step(cfg, mesh=mesh)
    opt_state = opt.init(sharded)
    batch = shard_batch(mesh, {"tokens": jnp.ones((4, 32), jnp.int32)})
    params2, opt_state, loss = jax.jit(step)(sharded, opt_state, batch)
    jax.block_until_ready(loss)
    assert jnp.isfinite(loss)


def test_top1_router_keeps_lm_gradient():
    """Switch top-1: the raw gate scales the expert output, so the router
    trains from the LM loss, not only the aux loss (k=1 must NOT renorm)."""
    cfg = TransformerConfig(
        vocab=64,
        d_model=32,
        n_layers=1,
        n_heads=2,
        d_ff=64,
        dtype=jnp.float32,
        use_flash=False,
        remat=False,
        moe=MoEConfig(
            n_experts=2,
            experts_per_token=1,
            capacity_factor=4.0,
            router_aux_weight=0.0,  # isolate the LM-loss path
        ),
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab)
    grads = jax.grad(loss_fn)(params, {"tokens": tokens}, cfg)
    assert float(jnp.sum(jnp.abs(grads["layers"]["router"]))) > 0


@pytest.mark.slow  # compile-heavy CPU-mesh parity (minutes): run via -m slow
def test_pp_moe_composition():
    """MoE composes with pipeline parallelism: pp=2 x ep=2, expert weights
    ep-sharded inside the stages (manual-collective MoE), aux threaded
    through the pipeline. With ample capacity (no token drops) the pipelined
    LM loss matches the non-pipelined MoE loss; gradients flow to the router
    and experts."""
    from odh_kubeflow_tpu.models import (
        make_pp_train_step,
        pp_loss_fn,
        pp_param_specs,
        to_pp_params,
    )
    from jax.sharding import NamedSharding

    cfg = TransformerConfig(
        vocab=64,
        d_model=32,
        n_layers=4,
        n_heads=2,
        d_ff=64,
        dtype=jnp.float32,
        use_flash=False,
        remat=False,
        moe=MoEConfig(n_experts=4, experts_per_token=2, capacity_factor=4.0),
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab)
    ref_loss = loss_fn(params, {"tokens": tokens}, cfg)

    plan = MeshPlan.auto(8, want_pp=2, want_ep=2)
    assert plan.pp == 2 and plan.ep == 2
    mesh = plan.build(jax.devices()[:8])
    pp_params = to_pp_params(params, 2, cfg, mesh)
    specs = pp_param_specs(cfg, mesh, 2)
    # expert weights keep their ep shard under the stage dim
    assert specs["layers"]["we_gate"] == jax.sharding.PartitionSpec("pp", None, "ep")
    pp_params = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), pp_params, specs
    )
    batch = shard_batch(mesh, {"tokens": tokens})
    loss = jax.jit(
        lambda p, b: pp_loss_fn(p, b, cfg, mesh, n_micro=2)
    )(pp_params, batch)
    # no drops at capacity_factor=4 -> per-token routing identical; only the
    # aux term (per-microbatch vs full-batch statistics) may differ slightly
    assert abs(float(loss) - float(ref_loss)) < 5e-3

    step, opt = make_pp_train_step(cfg, mesh, n_micro=2)
    opt_state = opt.init(pp_params)
    new_params, opt_state, loss2 = jax.jit(step)(pp_params, opt_state, batch)
    jax.block_until_ready(loss2)
    g = jax.jit(jax.grad(lambda p: pp_loss_fn(p, batch, cfg, mesh, n_micro=2)))(
        pp_params
    )
    assert float(jnp.sum(jnp.abs(g["layers"]["router"]))) > 0
    assert float(jnp.sum(jnp.abs(g["layers"]["we_gate"]))) > 0


def test_indexed_matches_dense_dispatch():
    """The indexed scatter/gather path and the dense one-hot einsum path
    consume the same route_indices decision, so their outputs agree exactly
    in f32 — including under oversubscription (dropped tokens)."""
    from dataclasses import replace

    from odh_kubeflow_tpu.models.moe import _moe_ffn_indexed

    rng = jax.random.PRNGKey(3)
    b, s, d = 2, 32, 16
    for cap, k in ((0.5, 2), (4.0, 2), (1.0, 1)):  # incl. heavy drops
        cfg = MoEConfig(n_experts=4, experts_per_token=k, capacity_factor=cap)
        params = init_moe_params(jax.random.PRNGKey(4), d, replace(cfg, d_ff=32),
                                 jnp.float32)
        x = jax.random.normal(rng, (b, s, d), jnp.float32)
        dense_cfg = replace(cfg, d_ff=32, dispatch="dense")
        out_dense, aux_dense = moe_ffn(x, params, dense_cfg)
        out_idx, aux_idx = _moe_ffn_indexed(x, params, replace(cfg, d_ff=32))
        np.testing.assert_allclose(np.asarray(out_dense), np.asarray(out_idx),
                                   rtol=1e-5, atol=1e-6)
        assert float(aux_dense) == float(aux_idx)


def test_indexed_dispatch_gradients():
    """Gradients flow through the indexed path to router AND experts."""
    from dataclasses import replace

    from odh_kubeflow_tpu.models.moe import _moe_ffn_indexed

    cfg = MoEConfig(n_experts=4, experts_per_token=2, capacity_factor=1.25,
                    d_ff=32)
    params = init_moe_params(jax.random.PRNGKey(0), 16, cfg, jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 16), jnp.float32)

    def loss(p):
        out, aux = _moe_ffn_indexed(x, p, cfg)
        return jnp.sum(out**2) + aux

    g = jax.grad(loss)(params)
    assert float(jnp.sum(jnp.abs(g["router"]))) > 0
    assert float(jnp.sum(jnp.abs(g["we_gate"]))) > 0
    assert float(jnp.sum(jnp.abs(g["we_out"]))) > 0


def test_dispatch_only_and_routing_stats():
    """bench.py helpers: dispatch_only round-trips tokens through slots
    (identity experts => output == gate-weighted input for kept tokens);
    routing_stats reports drop rate in [0, 1] and loads summing to 1."""
    from odh_kubeflow_tpu.models.moe import dispatch_only, routing_stats

    cfg = MoEConfig(n_experts=4, experts_per_token=1, capacity_factor=4.0,
                    d_ff=32)
    params = init_moe_params(jax.random.PRNGKey(0), 16, cfg, jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 16), jnp.float32)
    out = dispatch_only(x, params, cfg)
    assert out.shape == x.shape
    # top-1 with ample capacity: out = gate * x rowwise, gate in (0, 1]
    flat_x, flat_o = x.reshape(-1, 16), np.asarray(out).reshape(-1, 16)
    ratio = flat_o / np.asarray(flat_x)
    spread = ratio.max(axis=1) - ratio.min(axis=1)
    assert float(np.max(spread)) < 1e-5

    stats = routing_stats(x, params, cfg)
    assert 0.0 <= float(stats["drop_rate"]) <= 1.0
    assert np.isclose(float(jnp.sum(stats["expert_load_frac"])), 1.0)
    # capacity_factor 4 with 64 tokens over 4 experts: no drops expected
    assert float(stats["drop_rate"]) == 0.0


@pytest.mark.slow  # compile-heavy CPU-mesh parity (minutes): run via -m slow
def test_pp_moe_1f1b_parity():
    """VERDICT r4 #3: the 1F1B schedule threads the MoE aux channel — loss
    AND gradients match GPipe (autodiff through the aux-threaded pipeline)
    and the non-pipelined model, at pp=2 x ep=2 and with tp composed in.
    Ample capacity so routing is drop-free and per-token identical."""
    from jax.sharding import NamedSharding

    from odh_kubeflow_tpu.models import (
        pp_loss_fn,
        pp_param_specs,
        to_pp_params,
    )
    from odh_kubeflow_tpu.models.transformer import pp_1f1b_value_and_grad

    cfg = TransformerConfig(
        vocab=64,
        d_model=32,
        n_layers=4,
        n_heads=4,
        n_kv_heads=2,
        d_ff=64,
        dtype=jnp.float32,
        use_flash=False,
        remat=False,
        moe=MoEConfig(n_experts=4, experts_per_token=2, capacity_factor=8.0),
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab)
    ref_loss = loss_fn(params, {"tokens": tokens}, cfg)

    for plan_kw in ({"pp": 2, "ep": 2, "dp": 2}, {"pp": 2, "ep": 2, "tp": 2}):
        plan = MeshPlan(**plan_kw)
        mesh = plan.build(jax.devices()[:8])
        pp_params = to_pp_params(params, 2, cfg, mesh)
        specs = pp_param_specs(cfg, mesh, 2)
        pp_params_s = jax.tree_util.tree_map(
            lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
            pp_params, specs,
        )
        batch = shard_batch(mesh, {"tokens": tokens})

        # GPipe: capacity derives from per-microbatch counts; n_micro=4 so
        # both schedules see identical capacity -> identical routing
        g_loss, g_grads = jax.jit(jax.value_and_grad(
            lambda p: pp_loss_fn(p, batch, cfg, mesh, n_micro=4)
        ))(pp_params_s)
        f_loss, f_grads = jax.jit(
            lambda p, b: pp_1f1b_value_and_grad(p, b, cfg, mesh, n_micro=4)
        )(pp_params_s, batch)
        jax.block_until_ready(f_loss)

        assert np.allclose(float(f_loss), float(g_loss), atol=1e-6), plan_kw
        # vs non-pipelined: only the aux statistics window differs
        # (per-microbatch vs full batch)
        assert abs(float(f_loss) - float(ref_loss)) < 5e-3, plan_kw
        flat_g, _ = jax.tree_util.tree_flatten_with_path(g_grads)
        flat_f, _ = jax.tree_util.tree_flatten_with_path(f_grads)
        for (path_g, a), (path_f, b) in zip(flat_g, flat_f):
            assert path_g == path_f
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), atol=1e-6, rtol=1e-5,
                err_msg=f"{plan_kw} {jax.tree_util.keystr(path_g)}",
            )
        # the aux channel really reaches the router through 1F1B
        assert float(jnp.sum(jnp.abs(f_grads["layers"]["router"]))) > 0


@pytest.mark.slow  # compile-heavy CPU-mesh parity (minutes): run via -m slow
def test_pp_moe_interleaved_1f1b_parity():
    """The full composition: Megatron interleaved 1F1B (pp=2 x v=2) with
    ep-sharded MoE experts inside the chunks and the aux channel threaded —
    loss and gradients match interleaved GPipe."""
    from jax.sharding import NamedSharding

    from odh_kubeflow_tpu.models import (
        pp_loss_fn,
        pp_param_specs,
        to_pp_params,
    )
    from odh_kubeflow_tpu.models.transformer import pp_1f1b_value_and_grad

    cfg = TransformerConfig(
        vocab=64,
        d_model=32,
        n_layers=8,
        n_heads=4,
        n_kv_heads=2,
        d_ff=64,
        dtype=jnp.float32,
        use_flash=False,
        remat=False,
        moe=MoEConfig(n_experts=4, experts_per_token=2, capacity_factor=8.0),
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab)

    plan = MeshPlan(pp=2, ep=2, dp=2)
    mesh = plan.build(jax.devices()[:8])
    pp_params = to_pp_params(params, 2, cfg, mesh, n_chunks=2)
    specs = pp_param_specs(cfg, mesh, 2, n_chunks=2)
    pp_params = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), pp_params, specs
    )
    batch = shard_batch(mesh, {"tokens": tokens})

    g_loss, g_grads = jax.jit(jax.value_and_grad(
        lambda p: pp_loss_fn(p, batch, cfg, mesh, n_micro=4, n_chunks=2)
    ))(pp_params)
    f_loss, f_grads = jax.jit(
        lambda p, b: pp_1f1b_value_and_grad(
            p, b, cfg, mesh, n_micro=4, n_chunks=2
        )
    )(pp_params, batch)
    jax.block_until_ready(f_loss)

    assert np.allclose(float(f_loss), float(g_loss), atol=1e-6)
    flat_g, _ = jax.tree_util.tree_flatten_with_path(g_grads)
    flat_f, _ = jax.tree_util.tree_flatten_with_path(f_grads)
    for (path_g, a), (path_f, b) in zip(flat_g, flat_f):
        assert path_g == path_f
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=1e-6, rtol=1e-5,
            err_msg=jax.tree_util.keystr(path_g),
        )
    assert float(jnp.sum(jnp.abs(f_grads["layers"]["router"]))) > 0


def test_ep_indexed_matches_dense_on_mesh():
    """VERDICT r4 #7: the indexed dispatch is the live-ep GSPMD path. At
    ample capacity the shard_map'd indexed path (_moe_ffn_ep_indexed)
    produces the same outputs and expert/router gradients as the dense
    one-hot einsum path on the same ep mesh; only the aux statistics window
    differs (per-data-shard vs global batch)."""
    from dataclasses import replace
    from jax.sharding import NamedSharding

    from odh_kubeflow_tpu.models.moe import moe_ffn, init_moe_params

    plan = MeshPlan.auto(8, want_ep=2, want_tp=2)
    mesh = plan.build(jax.devices()[:8])
    d = 32
    cfg_idx = MoEConfig(
        n_experts=4, experts_per_token=2, capacity_factor=8.0, d_ff=64,
        dispatch="auto",
    )
    cfg_dense = replace(cfg_idx, dispatch="dense")
    params = init_moe_params(jax.random.PRNGKey(0), d, cfg_idx, jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16, d), jnp.float32)

    def run(cfg):
        def f(p, x):
            out, aux = moe_ffn(x, p, cfg, mesh=mesh)
            return jnp.sum(out**2), (out, aux)

        (loss, (out, aux)), grads = jax.jit(
            jax.value_and_grad(f, has_aux=True)
        )(params, x)
        jax.block_until_ready(loss)
        return out, aux, grads

    out_i, aux_i, g_i = run(cfg_idx)
    out_d, aux_d, g_d = run(cfg_dense)
    np.testing.assert_allclose(
        np.asarray(out_i), np.asarray(out_d), atol=1e-5, rtol=1e-5
    )
    for name in ("we_gate", "we_up", "we_out", "router"):
        np.testing.assert_allclose(
            np.asarray(g_i[name]), np.asarray(g_d[name]),
            atol=1e-5, rtol=1e-4, err_msg=name,
        )
    # aux windows differ (per-shard vs global) but both are O(1) balanced
    assert 0.5 < float(aux_i) / float(aux_d) < 2.0
