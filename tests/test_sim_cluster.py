"""SimCluster integration: STS -> pods -> scheduling -> readiness, TPU gang
placement all-or-nothing, scale down, template-change recreate."""
import pytest

from odh_kubeflow_tpu.api.apps import StatefulSet
from odh_kubeflow_tpu.api.core import Container, Event, Pod, ResourceRequirements
from odh_kubeflow_tpu.cluster import PodDecision, SimCluster
from odh_kubeflow_tpu.tpu import TPU_RESOURCE, plan_slice


@pytest.fixture()
def cluster():
    c = SimCluster()
    c.start()
    yield c
    c.stop()


def mk_sts(name, ns="user", replicas=1, tpu_chips=0, node_selector=None, image="img:1"):
    sts = StatefulSet()
    sts.metadata.name = name
    sts.metadata.namespace = ns
    sts.spec.replicas = replicas
    sts.spec.service_name = name
    sts.spec.selector.match_labels = {"app": name}
    sts.spec.template.metadata.labels = {"app": name}
    c = Container(name=name, image=image)
    if tpu_chips:
        c.resources = ResourceRequirements(
            requests={TPU_RESOURCE: str(tpu_chips)}, limits={TPU_RESOURCE: str(tpu_chips)}
        )
    sts.spec.template.spec.containers = [c]
    if node_selector:
        sts.spec.template.spec.node_selector = dict(node_selector)
    return sts


def wait_ready(cluster, ns, name, want, timeout=10):
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        sts = cluster.client.get(StatefulSet, ns, name)
        if sts.status.ready_replicas == want:
            return sts
        time.sleep(0.05)
    raise AssertionError(
        f"{ns}/{name} never reached {want} ready "
        f"(at {cluster.client.get(StatefulSet, ns, name).status.ready_replicas})"
    )


def test_cpu_sts_becomes_ready(cluster):
    cluster.add_cpu_pool("default-pool", nodes=2)
    cluster.client.create(mk_sts("web", replicas=2))
    sts = wait_ready(cluster, "user", "web", 2)
    assert sts.status.replicas == 2
    pods = cluster.client.list(Pod, namespace="user")
    assert sorted(p.metadata.name for p in pods) == ["web-0", "web-1"]
    assert all(p.spec.node_name for p in pods)
    assert pods[0].metadata.labels["apps.kubernetes.io/pod-index"] == "0"
    assert pods[0].spec.hostname == "web-0"
    assert pods[0].spec.subdomain == "web"


def test_multi_host_tpu_gang_placement(cluster):
    shape = plan_slice("v5p", topology="2x2x4")
    cluster.add_tpu_pool("v5p-pool", "v5p", "2x2x4")
    sts = mk_sts(
        "trainer", replicas=shape.hosts, tpu_chips=shape.chips_per_host,
        node_selector=shape.node_selector(),
    )
    cluster.client.create(sts)
    wait_ready(cluster, "user", "trainer", 4)
    pods = cluster.client.list(Pod, namespace="user")
    nodes = {p.spec.node_name for p in pods}
    assert len(nodes) == 4  # one pod per host
    # all in the same pool (same ICI slice)
    from odh_kubeflow_tpu.api.core import Node
    pools = {
        cluster.client.get(Node, "", n).metadata.labels["cloud.google.com/gke-nodepool"]
        for n in nodes
    }
    assert len(pools) == 1


def test_gang_all_or_nothing(cluster):
    # pool has 4 hosts; ask for 8 -> nothing schedules, events emitted
    shape = plan_slice("v5p", topology="2x2x4")
    cluster.add_tpu_pool("small-pool", "v5p", "2x2x4")
    sts = mk_sts(
        "big", replicas=8, tpu_chips=4, node_selector=shape.node_selector()
    )
    cluster.client.create(sts)
    import time

    time.sleep(1.0)
    pods = cluster.client.list(Pod, namespace="user")
    assert len(pods) == 8
    assert all(not p.spec.node_name for p in pods)  # all-or-nothing held
    events = cluster.client.list(Event, namespace="user")
    assert any(e.reason == "FailedScheduling" for e in events)


def test_two_slices_no_mixing(cluster):
    # two 2-host v5e slices; a 2-host workload lands entirely in one
    shape = plan_slice("v5e", topology="2x4")
    # force multi-host by using 4x4 (4 hosts)? use 2 slices of 4x4
    shape = plan_slice("v5e", topology="4x4")
    cluster.add_tpu_pool("v5e", "v5e", "4x4", slices=2)
    sts = mk_sts(
        "t2", replicas=4, tpu_chips=4, node_selector=shape.node_selector()
    )
    cluster.client.create(sts)
    wait_ready(cluster, "user", "t2", 4)
    from odh_kubeflow_tpu.api.core import Node
    pools = set()
    for p in cluster.client.list(Pod, namespace="user"):
        node = cluster.client.get(Node, "", p.spec.node_name)
        pools.add(node.metadata.labels["cloud.google.com/gke-nodepool"])
    assert len(pools) == 1


def test_scale_down_to_zero(cluster):
    cluster.add_cpu_pool("p", nodes=1)
    cluster.client.create(mk_sts("nb"))
    wait_ready(cluster, "user", "nb", 1)
    sts = cluster.client.get(StatefulSet, "user", "nb")
    sts.spec.replicas = 0
    cluster.client.update(sts)
    wait_ready(cluster, "user", "nb", 0)
    import time

    time.sleep(0.2)
    assert cluster.client.list(Pod, namespace="user") == []


def test_template_change_recreates_pod(cluster):
    cluster.add_cpu_pool("p", nodes=1)
    cluster.client.create(mk_sts("nb", image="img:1"))
    wait_ready(cluster, "user", "nb", 1)
    uid0 = cluster.client.get(Pod, "user", "nb-0").metadata.uid
    sts = cluster.client.get(StatefulSet, "user", "nb")
    sts.spec.template.spec.containers[0].image = "img:2"
    cluster.client.update(sts)
    import time

    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        try:
            p = cluster.client.get(Pod, "user", "nb-0")
            if p.metadata.uid != uid0 and p.status.phase == "Running":
                assert p.spec.containers[0].image == "img:2"
                return
        except Exception:
            pass
        time.sleep(0.05)
    raise AssertionError("pod never recreated with new template")


def test_pod_behavior_failure(cluster):
    cluster.add_cpu_pool("p", nodes=1)
    cluster.add_pod_behavior(
        lambda pod: PodDecision(fail="ImagePullBackOff")
        if pod.spec.containers and pod.spec.containers[0].image == "bad:tag"
        else None
    )
    cluster.client.create(mk_sts("broken", image="bad:tag"))
    import time

    time.sleep(0.5)
    pod = cluster.client.get(Pod, "user", "broken-0")
    assert pod.status.phase == "Pending"
    assert pod.status.container_statuses[0].state.waiting["reason"] == "ImagePullBackOff"


def test_gang_reschedules_when_capacity_frees(cluster):
    """ISSUE 4 satellite: an Unschedulable gang is re-attempted the moment
    pool capacity frees (scheduled-pod deletion / node events), not on the
    next incidental event or backoff poll — proven by cranking the backoff
    far beyond the test budget so only the capacity-freed watch can win."""
    import time

    shape = plan_slice("v5p", topology="2x2x2")  # 2 hosts
    cluster.add_tpu_pool("pool-a", "v5p", "2x2x2")
    cluster.client.create(
        mk_sts("squatter", replicas=2, tpu_chips=4,
               node_selector=shape.node_selector())
    )
    wait_ready(cluster, "user", "squatter", 2)

    # any unschedulable requeue now sleeps 60s: rescheduling within the test
    # budget MUST come from the event-driven capacity-freed path
    cluster.scheduler.backoff_base_s = 60.0
    cluster.scheduler.backoff_max_s = 60.0
    cluster.client.create(
        mk_sts("waiter", replicas=2, tpu_chips=4,
               node_selector=shape.node_selector())
    )
    time.sleep(1.0)  # waiter fails at least one pass and enters backoff
    waiter_pods = [
        p for p in cluster.client.list(Pod, namespace="user")
        if p.metadata.name.startswith("waiter")
    ]
    assert len(waiter_pods) == 2
    assert all(not p.spec.node_name for p in waiter_pods), "all-or-nothing held"
    events = cluster.client.list(Event, namespace="user")
    assert any(e.reason == "FailedScheduling" for e in events)

    # free the pool: the squatter scales away; its pods' DELETED events are
    # the capacity-freed signal
    sts = cluster.client.get(StatefulSet, "user", "squatter")
    sts.spec.replicas = 0
    cluster.client.update(sts)
    wait_ready(cluster, "user", "waiter", 2, timeout=10)


def test_preempted_node_drains_and_takes_no_new_pods(cluster):
    """Host preemption substrate: the maintenance notice holds pods through
    the grace window, the drain then kills them, and the tainted/NotReady
    node is excluded from scheduling until restored."""
    import time

    from odh_kubeflow_tpu.api.core import Node
    from odh_kubeflow_tpu.cluster.faults import PREEMPTION_TAINT_KEY

    shape = plan_slice("v5e", topology="2x2")  # single host
    cluster.add_tpu_pool("solo", "v5e", "2x2")
    cluster.client.create(
        mk_sts("nb", replicas=1, tpu_chips=4, node_selector=shape.node_selector())
    )
    sts = wait_ready(cluster, "user", "nb", 1)
    node_name = cluster.client.get(Pod, "user", "nb-0").spec.node_name

    cluster.preempt_node(node_name, grace_s=0.4)
    # within the grace window the pod is still alive (checkpoint opportunity)
    pod = cluster.client.get(Pod, "user", "nb-0")
    assert pod.is_ready() and not pod.metadata.deletion_timestamp

    # after the window: drained, node NotReady, replacement pod unschedulable
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        node = cluster.client.get(Node, "", node_name)
        if any(c.type == "Ready" and c.status == "False"
               for c in node.status.conditions):
            break
        time.sleep(0.05)
    node = cluster.client.get(Node, "", node_name)
    assert any(t["key"] == PREEMPTION_TAINT_KEY for t in node.spec["taints"])
    assert any(c.type == "Ready" and c.status == "False"
               for c in node.status.conditions)
    time.sleep(0.5)
    pod = cluster.client.get(Pod, "user", "nb-0")  # recreated by the STS
    assert not pod.spec.node_name, "scheduler placed a pod on a drained node"

    # maintenance ends: capacity returns and the pod lands again
    cluster.restore_node(node_name)
    wait_ready(cluster, "user", "nb", 1)


def test_cpu_pods_never_land_on_tpu_hosts(cluster):
    # GKE TPU pools are tainted google.com/tpu: CPU pods must avoid them
    cluster.add_tpu_pool("tpu-pool", "v5e", "2x2")
    cluster.add_cpu_pool("cpu-pool", nodes=1)
    cluster.client.create(mk_sts("plain", replicas=1))
    wait_ready(cluster, "user", "plain", 1)
    pod = cluster.client.get(Pod, "user", "plain-0")
    assert pod.spec.node_name.startswith("cpu-pool")
