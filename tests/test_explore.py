"""Systematic interleaving explorer + INVCHECK invariant monitor (ISSUE 8).

Three layers:

1. the INVCHECK store hook in isolation: declared machine transitions pass,
   undeclared ones raise at the write; a stolen pool claim raises; the hook
   is absent (None) unless armed,
2. the explorer acceptance gate: a bounded EXHAUSTIVE run over the
   suspend x repair x reclaim interleaving space of the SHIPPED controllers
   quiesces every schedule with zero invariant violations,
3. the explorer can FAIL: both seeded known-bad mutants (a suspend that
   skips the checkpoint window, a pool claim that ignores the lead-node
   CAS) are deterministically reproduced with a minimized, replayable
   interleaving trace — a detector that cannot detect is not a gate.

Plus the calm-path bound: an armed monitor adds <10% per store write
(min-of-runs, 0.5 ms noise floor — the PR 5 SLO-engine methodology).
"""
import logging

import pytest

from odh_kubeflow_tpu.analysis import explore as E
from odh_kubeflow_tpu.analysis.machines import (
    ALL_MACHINES,
    MACHINES,
    render_markdown,
    spec_errors,
)
from odh_kubeflow_tpu.cluster.slicepool import (
    POOL_CLAIMED_BY_ANNOTATION,
    POOL_STATE_ANNOTATION,
)
from odh_kubeflow_tpu.cluster.store import Store
from odh_kubeflow_tpu.controllers import constants as C
from odh_kubeflow_tpu.utils import invcheck

pytestmark = pytest.mark.analysis


@pytest.fixture(autouse=True)
def _quiet():
    # hundreds of schedules replay cull/reclaim/repair logs otherwise
    logging.disable(logging.CRITICAL)
    yield
    logging.disable(logging.NOTSET)


# ---------------------------------------------------------------------------
# machine specs are self-consistent (the data the whole subsystem trusts)
# ---------------------------------------------------------------------------


def test_machine_specs_validate():
    for spec in ALL_MACHINES:
        assert spec_errors(spec) == (), spec.name


def test_machine_spec_dead_end_is_an_error():
    from dataclasses import replace

    from odh_kubeflow_tpu.analysis.machines import SUSPEND_MACHINE, State

    bad_states = tuple(
        State(s.name, s.title, s.doc, s.terminal, False, False)
        if s.name == "resume-failed" else s
        for s in SUSPEND_MACHINE.states
    )
    bad = replace(SUSPEND_MACHINE, states=bad_states)
    assert any("dead end" in e for e in spec_errors(bad))


def test_render_markdown_covers_every_machine_and_state():
    doc = render_markdown()
    for spec in ALL_MACHINES:
        assert f"`{spec.name}`" in doc
        for state in spec.states:
            assert state.title in doc


def test_architecture_embeds_the_current_contract():
    # ARCHITECTURE.md round 9 claims the tables are generated — hold it to
    # that: the embedded block must BE the current render, byte for byte
    import pathlib

    import odh_kubeflow_tpu

    repo = pathlib.Path(odh_kubeflow_tpu.__file__).parent.parent
    text = (repo / "ARCHITECTURE.md").read_text()
    assert render_markdown().strip() in text, (
        "ARCHITECTURE.md machine tables drifted from analysis/machines.py — "
        "re-embed with `python -m odh_kubeflow_tpu.analysis --machines-doc`"
    )


# ---------------------------------------------------------------------------
# INVCHECK monitor in isolation
# ---------------------------------------------------------------------------


def _nb_dict(name, annotations):
    return {
        "apiVersion": "kubeflow.org/v1beta1",
        "kind": "Notebook",
        "metadata": {"name": name, "namespace": "inv",
                     "annotations": dict(annotations)},
    }


def test_invcheck_passes_declared_transitions():
    store = Store(backend="python", invariants=invcheck.Monitor())
    store.create_raw(_nb_dict("nb", {}))
    for ann in (
        {C.TPU_SUSPEND_STATE_ANNOTATION: "checkpointing",
         C.STOP_ANNOTATION: "2024-01-01T00:00:00Z"},
        {C.TPU_SUSPEND_STATE_ANNOTATION: "suspended"},
        {C.TPU_SUSPEND_STATE_ANNOTATION: "resuming",
         C.STOP_ANNOTATION: None},
        {C.TPU_SUSPEND_STATE_ANNOTATION: None},
    ):
        store.patch_raw("kubeflow.org/v1beta1", "Notebook", "inv", "nb",
                        {"metadata": {"annotations": ann}})


def test_invcheck_raises_on_undeclared_transition():
    store = Store(backend="python", invariants=invcheck.Monitor())
    store.create_raw(_nb_dict("nb", {}))
    # reach Suspended along declared edges first...
    for ann in (
        {C.TPU_SUSPEND_STATE_ANNOTATION: "checkpointing",
         C.STOP_ANNOTATION: "2024-01-01T00:00:00Z"},
        {C.TPU_SUSPEND_STATE_ANNOTATION: "suspended"},
    ):
        store.patch_raw("kubeflow.org/v1beta1", "Notebook", "inv", "nb",
                        {"metadata": {"annotations": ann}})
    with pytest.raises(invcheck.InvariantViolation, match="not a declared"):
        # ...then jump suspended -> checkpointing, skipping the resume half
        store.patch_raw(
            "kubeflow.org/v1beta1", "Notebook", "inv", "nb",
            {"metadata": {"annotations": {
                C.TPU_SUSPEND_STATE_ANNOTATION: "checkpointing"}}},
        )


def test_invcheck_raises_on_stolen_pool_claim():
    store = Store(backend="python", invariants=invcheck.Monitor())
    store.create_raw({
        "apiVersion": "v1", "kind": "Node",
        "metadata": {"name": "n1", "annotations": {
            POOL_STATE_ANNOTATION: "claimed",
            POOL_CLAIMED_BY_ANNOTATION: "ns/alice",
        }},
    })
    with pytest.raises(invcheck.InvariantViolation, match="stolen"):
        store.patch_raw("v1", "Node", "", "n1", {
            "metadata": {"annotations": {
                POOL_CLAIMED_BY_ANNOTATION: "ns/bob"}},
        })


def test_invcheck_off_by_default(monkeypatch):
    monkeypatch.delenv("INVCHECK", raising=False)
    assert Store(backend="python").invariants is None
    monkeypatch.setenv("INVCHECK", "1")
    assert isinstance(Store(backend="python").invariants, invcheck.Monitor)


# ---------------------------------------------------------------------------
# acceptance: bounded exhaustive run over the shipped controllers
# ---------------------------------------------------------------------------


def test_exhaustive_interleaving_space_is_clean():
    result = E.explore_default()
    assert result.exhausted, "scheduler budget exceeded before the frontier drained"
    assert result.truncated == 0, "depth bound cut schedules short"
    assert result.schedules > 0, "no schedule ever reached quiescence"
    assert result.violations == [], "\n".join(
        f"[{v.invariant}] {v.detail}\n  trace: {' -> '.join(v.trace)}"
        for v in result.violations
    )


def test_steady_checks_have_teeth():
    # wedge a notebook by hand: a resuming state nobody will ever advance
    # must read as stuck at quiescence — the contract test for the leaf
    # checks the exhaustive run relies on
    world = E.World()
    world.store.invariants = None  # scripted wedge, not an observed write
    world.client.patch(
        E.Notebook, E.NS, "nb2",
        {"metadata": {"annotations": {
            C.TPU_SUSPEND_STATE_ANNOTATION: "resuming",
            C.STOP_ANNOTATION: None,
        }}},
    )
    names = {v.invariant for v in E.steady_violations(world)}
    assert "stuck-state" in names


@pytest.mark.slow
def test_exhaustive_with_one_preemption_is_clean():
    # the wider space (one arbitrary preemptive switch anywhere): ~3 min,
    # soak-lane territory
    result = E.explore_default(max_preemptions=1)
    assert result.ok, "\n".join(
        f"[{v.invariant}] {v.detail}" for v in result.violations
    )


def test_exhaustive_job_space_is_clean():
    # ISSUE 10 acceptance: the job-vs-suspend-vs-reclaim space (warm-claim
    # admission steals the suspended notebook's slice, the resume pressures
    # the REAL reclaimer into checkpoint-preempting the REAL job
    # controller, the job requeues and re-admits) exhausts clean
    result = E.explore_jobs()
    assert result.exhausted, "scheduler budget exceeded before the frontier drained"
    assert result.truncated == 0, "depth bound cut schedules short"
    assert result.schedules > 0, "no schedule ever reached quiescence"
    assert result.violations == [], "\n".join(
        f"[{v.invariant}] {v.detail}\n  trace: {' -> '.join(v.trace)}"
        for v in result.violations
    )


def test_job_steady_check_has_teeth():
    # a job wedged in Admitted with every actor idle must read as stuck at
    # quiescence — the leaf check the job space's silent-stuck gate relies on
    world = E.JobWorld()
    world.store.invariants = None  # scripted wedge, not an observed write
    from odh_kubeflow_tpu.api.job import TPUJob

    world.client.patch(
        TPUJob, E.NS, "job1",
        {"metadata": {"annotations": {C.JOB_STATE_ANNOTATION: "admitted"}}},
    )
    names = {v.invariant for v in E.steady_violations(world)}
    assert "stuck-state" in names


@pytest.mark.slow
def test_exhaustive_job_space_with_churn_is_clean():
    # the full three-actor space (interactive cull/suspend actors on top of
    # the job/reclaim ops): soak-lane territory
    result = E.explore_jobs(churn_ops=True)
    assert result.ok, "\n".join(
        f"[{v.invariant}] {v.detail}" for v in result.violations
    )


# ---------------------------------------------------------------------------
# the explorer can fail: seeded known-bad mutants
# ---------------------------------------------------------------------------


def test_mutant_skip_checkpoint_is_reproduced_and_minimized():
    first, minimized = E.explore_mutant("skip-checkpoint")
    assert first.invariant == "checkpoint-before-suspend"
    # deterministic: same schedule and same minimized trace every run
    first2, minimized2 = E.explore_mutant("skip-checkpoint")
    assert (first.trace, minimized) == (first2.trace, minimized2)
    # the minimized trace is tiny and replayable: cull stamps
    # checkpointing, the mutant suspend skips the window
    assert len(minimized) <= 4
    assert minimized[-1] == "suspend-1"
    explorer = E.Explorer(E.MUTANTS["skip-checkpoint"])
    replayed = explorer.replay(minimized)
    assert any(v.invariant == "checkpoint-before-suspend" for v in replayed)


def test_mutant_cas_blind_claim_is_reproduced_and_minimized():
    first, minimized = E.explore_mutant("cas-blind")
    assert first.invariant == "pool-claim-cas"
    first2, minimized2 = E.explore_mutant("cas-blind")
    assert (first.trace, minimized) == (first2.trace, minimized2)
    # resume claims the warm slice; the blind rival steals it
    assert minimized[-1] == "rival-cas"
    assert len(minimized) <= 4
    explorer = E.Explorer(E.MUTANTS["cas-blind"])
    replayed = explorer.replay(minimized)
    assert any(v.invariant == "pool-claim-cas" for v in replayed)


def test_shipped_controllers_pass_where_mutants_fail():
    # the exact minimized mutant schedules, replayed against the SHIPPED
    # controllers, stay clean — the violations are the mutations' own
    explorer = E.Explorer(E.World)
    for trace in (("cull-1", "suspend-1"),
                  ("unstop-2", "suspend-2", "rival-cas")):
        assert explorer.replay(trace) == []


# ---------------------------------------------------------------------------
# calm-path overhead: INVCHECK < 10% per write
# ---------------------------------------------------------------------------


def test_invcheck_overhead_under_ten_percent():
    E.overhead_ratio(n=30)  # warm imports/JITs before measuring
    base_per, on_per = E.overhead_ratio()
    added_per = max(0.0, on_per - base_per)
    assert added_per < max(0.10 * base_per, 0.0005), (
        f"INVCHECK adds {added_per * 1e3:.3f} ms per write "
        f"({added_per / base_per:.0%} of the {base_per * 1e3:.3f} ms "
        "baseline)"
    )
