"""L8 ops: flash-attention kernel vs reference, ring attention over the sp
mesh axis, RMSNorm, RoPE. Runs on the 8-device virtual CPU mesh (conftest)."""
from functools import partial

import jax
import jax.numpy as jnp
import pytest

from odh_kubeflow_tpu.ops import (
    apply_rope,
    flash_attention,
    mha_reference,
    ring_attention,
    rms_norm,
)
from odh_kubeflow_tpu.parallel import MeshPlan
from odh_kubeflow_tpu.parallel.mesh import logical_to_spec


def qkv(b=2, s=256, h=4, d=64, dtype=jnp.float32):
    return tuple(
        jax.random.normal(jax.random.PRNGKey(i), (b, s, h, d), dtype)
        for i in range(3)
    )


@pytest.mark.parametrize("causal", [True, False])
def test_flash_kernel_matches_reference(causal):
    q, k, v = qkv()
    ref = mha_reference(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    assert jnp.max(jnp.abs(out - ref)) < 2e-2  # online-softmax reassociation
    assert out.dtype == q.dtype


@pytest.mark.parametrize("causal", [True, False])
def test_flash_kernel_gqa_native(causal):
    # kv_heads < heads: the kernel streams un-expanded K/V (no repeat_kv)
    q, _, _ = qkv(h=8)
    _, k, v = qkv(h=2)
    ref = mha_reference(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    assert jnp.max(jnp.abs(out - ref)) < 2e-2


@pytest.mark.parametrize("kv_heads", [4, 1])
def test_flash_backward_matches_reference_grads(kv_heads):
    q, _, _ = qkv(s=256, h=4)
    _, k, v = qkv(s=256, h=kv_heads)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        scale = jnp.maximum(jnp.max(jnp.abs(b)), 1.0)
        assert jnp.max(jnp.abs(a - b)) / scale < 2e-2, name


@pytest.mark.parametrize("kv_heads", [2, 1])
def test_flash_balanced_causal_grid(kv_heads):
    """Small blocks force num_qb == num_kb == 4 (even): the work-balanced
    causal grid (paired q rows, N+1 inner steps) must match the reference,
    forward and backward."""
    from odh_kubeflow_tpu.ops.attention import _use_balanced

    assert _use_balanced(True, 128, 128, 4, 4)
    q, _, _ = qkv(s=512, h=2, d=64)
    _, k, v = qkv(s=512, h=kv_heads, d=64)

    def loss_flash(q, k, v):
        out = flash_attention(
            q, k, v, causal=True, block_q=128, block_k=128, interpret=True
        )
        return jnp.sum(out**2), out

    def loss_ref(q, k, v):
        out = mha_reference(q, k, v, causal=True)
        return jnp.sum(out**2), out

    (_, out), g_flash = jax.value_and_grad(loss_flash, argnums=(0, 1, 2), has_aux=True)(q, k, v)
    (_, ref), g_ref = jax.value_and_grad(loss_ref, argnums=(0, 1, 2), has_aux=True)(q, k, v)
    assert jnp.max(jnp.abs(out - ref)) < 2e-2
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        scale = jnp.maximum(jnp.max(jnp.abs(b)), 1.0)
        assert jnp.max(jnp.abs(a - b)) / scale < 2e-2, name


def test_flash_falls_back_off_tpu():
    q, k, v = qkv(s=100)  # not block-divisible -> reference path
    out = flash_attention(q, k, v, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    assert jnp.allclose(out, ref, atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_exact(causal):
    q, k, v = qkv(s=128)
    mesh = MeshPlan(sp=8).build()
    spec = logical_to_spec(("batch", "seq", "heads", "head_dim"), mesh)
    fn = jax.shard_map(
        partial(ring_attention, axis_name="sp", causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    out = jax.jit(fn)(q, k, v)
    ref = mha_reference(q, k, v, causal=causal)
    assert jnp.max(jnp.abs(out - ref)) < 1e-5  # exact: same f32 accumulation


def test_rms_norm():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 32), jnp.bfloat16)
    scale = jnp.ones((32,), jnp.bfloat16) * 2
    out = rms_norm(x, scale)
    assert out.dtype == jnp.bfloat16
    xf = x.astype(jnp.float32)
    want = xf / jnp.sqrt(jnp.mean(xf**2, -1, keepdims=True) + 1e-6) * 2
    assert jnp.max(jnp.abs(out.astype(jnp.float32) - want)) < 0.05


def test_rope_position_zero_is_identity_and_rotation_preserves_norm():
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 8, 2, 16), jnp.float32)
    pos0 = jnp.zeros((1, 8), jnp.int32)
    assert jnp.allclose(apply_rope(x, pos0), x, atol=1e-6)
    pos = jnp.arange(8, dtype=jnp.int32)[None, :]
    rotated = apply_rope(x, pos)
    assert jnp.allclose(
        jnp.linalg.norm(rotated, axis=-1), jnp.linalg.norm(x, axis=-1), atol=1e-4
    )


def test_rope_relative_phase():
    """Score q_i . k_j after RoPE depends only on i - j (the RoPE property
    ring attention relies on when shards apply global positions)."""
    d = 16
    q = jnp.ones((1, 1, 1, d))
    k = jnp.ones((1, 1, 1, d))

    def score(qi, kj):
        qr = apply_rope(q, jnp.array([[qi]], jnp.int32))
        kr = apply_rope(k, jnp.array([[kj]], jnp.int32))
        return float(jnp.sum(qr * kr))

    assert score(5, 3) == pytest.approx(score(12, 10), abs=1e-4)


def test_flash_kernel_causal_sq_longer_than_sk():
    """K-loop bound must clamp to the K extent (regression: qi past the last
    K block read out of bounds when sq > sk)."""
    q, _, _ = qkv(s=256)
    _, k, v = qkv(s=128)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = mha_reference(q, k, v, causal=True)
    assert jnp.max(jnp.abs(out - ref)) < 2e-2


@pytest.mark.parametrize("causal", [True, False])
def test_flash_backward_matches_reference_grads_interpret(causal):
    """The blockwise pallas backward (FlashAttention-2 recompute) produces
    the same gradients as differentiating the reference math — interpret
    mode, so this guards the kernel on CPU CI."""
    key = jax.random.PRNGKey(7)
    b, s, h, d = 1, 256, 2, 128
    q = jax.random.normal(key, (b, s, h, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(8), (b, s, h, d), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(9), (b, s, h, d), jnp.float32)

    def loss_flash(q, k, v):
        out = flash_attention(
            q, k, v, causal=causal, block_q=128, block_k=128, interpret=True
        )
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=causal).astype(jnp.float32) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b_ in zip("qkv", gf, gr):
        assert jnp.allclose(a, b_, atol=2e-3, rtol=2e-3), name


def test_flash_forward_lse_layout_interpret():
    """The forward's saved lse equals logsumexp of the (scaled, masked)
    scores, in the lane-broadcast kernel layout."""
    from odh_kubeflow_tpu.ops.attention import _flash_forward_kernel

    key = jax.random.PRNGKey(3)
    b, s, h, d = 1, 256, 2, 128
    q = jax.random.normal(key, (b, s, h, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(4), (b, s, h, d), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(5), (b, s, h, d), jnp.float32)
    out, lse = _flash_forward_kernel(
        q, k, v, causal=True, block_q=128, block_k=128, interpret=True, with_lse=True
    )
    # grouped layout: (batch*kv_heads, group, seq, 128); MHA -> group == 1
    assert lse.shape == (b * h, 1, s, 128)
    lse = lse[:, 0]
    # lane-broadcast: all 128 lanes carry the same value
    assert jnp.allclose(lse[..., 0], lse[..., 64])
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    scores = jnp.einsum("zqd,zkd->zqk", qt, kt) * (d**-0.5)
    mask = jnp.tril(jnp.ones((s, s), bool))
    scores = jnp.where(mask[None], scores, -1e30)
    expected = jax.scipy.special.logsumexp(scores, axis=-1)
    assert jnp.allclose(lse[..., 0], expected, atol=1e-3, rtol=1e-3)


@pytest.mark.parametrize("sp,kv_heads", [(2, 2), (4, 1), (2, 4)])
def test_ring_attention_gqa_native(sp, kv_heads):
    """Ring attention consumes kv_heads < heads natively (no K/V expansion
    anywhere in the repo — repeat_kv is gone): parity vs mha_reference on
    the full sequence, sp in {2, 4}."""
    q, _, _ = qkv(s=128, h=4)
    _, k, v = qkv(s=128, h=kv_heads)
    mesh = MeshPlan(sp=sp).build(jax.devices()[:sp])
    q_spec = logical_to_spec(("batch", "seq", "heads", "head_dim"), mesh)
    kv_spec = logical_to_spec(("batch", "seq", "kv_heads", "head_dim"), mesh)
    fn = jax.shard_map(
        partial(ring_attention, axis_name="sp", causal=True),
        mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec),
        out_specs=q_spec,
        check_vma=False,
    )
    out = jax.jit(fn)(q, k, v)
    ref = mha_reference(q, k, v, causal=True)
    assert jnp.max(jnp.abs(out - ref)) < 1e-5


@pytest.mark.parametrize("kv_heads", [4, 2])
def test_ring_attention_reference_grads(kv_heads):
    """Gradients through the (reference-path) ring match differentiating
    mha_reference — q, k AND v, with GQA group accumulation."""
    q, _, _ = qkv(s=128, h=4)
    _, k, v = qkv(s=128, h=kv_heads)
    mesh = MeshPlan(sp=2).build(jax.devices()[:2])
    q_spec = logical_to_spec(("batch", "seq", "heads", "head_dim"), mesh)
    kv_spec = logical_to_spec(("batch", "seq", "kv_heads", "head_dim"), mesh)
    fn = jax.shard_map(
        partial(ring_attention, axis_name="sp", causal=True),
        mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec),
        out_specs=q_spec,
        check_vma=False,
    )

    def loss_ring(q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gm = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gr, gm):
        scale = jnp.maximum(jnp.max(jnp.abs(b)), 1.0)
        assert jnp.max(jnp.abs(a - b)) / scale < 1e-5, name


@pytest.mark.slow  # compile-heavy CPU-mesh parity (minutes): run via -m slow
@pytest.mark.parametrize("sp,causal,kv_heads", [(2, True, 2), (2, True, 4),
                                                (2, False, 4), (4, True, 1)])
def test_ring_attention_kernel_path_interpret(sp, causal, kv_heads):
    """The pallas-block ring (per-visit flash kernel + lse merge, custom
    VJP backward ring) matches mha_reference forward AND backward —
    interpret mode, so the kernel composition is guarded on CPU CI."""
    from odh_kubeflow_tpu.ops.ring_attention import _ring_kernel

    q, _, _ = qkv(s=512, h=4)   # per-shard seq >= 128 so blocks fit
    _, k, v = qkv(s=512, h=kv_heads)
    mesh = MeshPlan(sp=sp).build(jax.devices()[:sp])
    q_spec = logical_to_spec(("batch", "seq", "heads", "head_dim"), mesh)
    kv_spec = logical_to_spec(("batch", "seq", "kv_heads", "head_dim"), mesh)
    fn = jax.shard_map(
        partial(_ring_kernel, axis_name="sp", causal=causal, interpret=True),
        mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec),
        out_specs=q_spec,
        check_vma=False,
    )
    out = jax.jit(fn)(q, k, v)
    ref = mha_reference(q, k, v, causal=causal)
    assert jnp.max(jnp.abs(out - ref)) < 2e-2

    def loss_ring(q, k, v):
        return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=causal).astype(jnp.float32) ** 2)

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gm = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gr, gm):
        scale = jnp.maximum(jnp.max(jnp.abs(b)), 1.0)
        assert jnp.max(jnp.abs(a - b)) / scale < 2e-2, name


@pytest.mark.slow  # compile-heavy CPU-mesh parity (minutes): run via -m slow
@pytest.mark.parametrize("sp,kv_heads,kernel", [(2, 2, False), (4, 1, False),
                                                (2, 4, True), (2, 2, True)])
def test_zigzag_ring_attention_parity(sp, kv_heads, kernel):
    """Zigzag (load-balanced) causal ring: with shards holding
    [chunk r | chunk 2S-1-r], outputs and q/k/v gradients equal natural-
    order attention permuted into zigzag storage order — reference path and
    pallas-block kernel path (interpret)."""
    import numpy as np

    from odh_kubeflow_tpu.ops.ring_attention import (
        ring_attention_zigzag,
        zigzag_permutation,
    )

    s_total = 1024 if kernel else 256  # kernel path needs chunk >= 128
    q, _, _ = qkv(s=s_total, h=4)
    _, k, v = qkv(s=s_total, h=kv_heads)
    perm = zigzag_permutation(s_total, sp)
    qz, kz, vz = q[:, perm], k[:, perm], v[:, perm]

    mesh = MeshPlan(sp=sp).build(jax.devices()[:sp])
    q_spec = logical_to_spec(("batch", "seq", "heads", "head_dim"), mesh)
    kv_spec = logical_to_spec(("batch", "seq", "kv_heads", "head_dim"), mesh)
    fn = jax.shard_map(
        partial(ring_attention_zigzag, axis_name="sp", interpret=kernel,
                use_kernel=kernel),
        mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec),
        out_specs=q_spec,
        check_vma=False,
    )
    out = jax.jit(fn)(qz, kz, vz)
    ref = mha_reference(q, k, v, causal=True)[:, perm]
    tol = 2e-2 if kernel else 1e-5
    assert jnp.max(jnp.abs(out - ref)) < tol

    def loss_zz(q_, k_, v_):
        return jnp.sum(fn(q_, k_, v_).astype(jnp.float32) ** 2)

    def loss_ref(q_, k_, v_):
        return jnp.sum(
            mha_reference(q_, k_, v_, causal=True)[:, perm].astype(jnp.float32)
            ** 2
        )

    gz = jax.jit(jax.grad(loss_zz, argnums=(0, 1, 2)))(qz, kz, vz)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gz, gr):
        want = np.asarray(b)[:, perm]
        scale = max(float(np.max(np.abs(want))), 1.0)
        assert float(np.max(np.abs(np.asarray(a) - want))) / scale < tol, name


def test_flash_block_with_lse_merge_grads():
    """flash_block_with_lse is a differentiable building block: composing
    two K/V blocks via the log-sum-exp _merge must match attention over the
    concatenated K/V — values AND q/k/v gradients (this exercises the lse
    cotangent folded into the backward's delta)."""
    import numpy as np

    from odh_kubeflow_tpu.ops.ring_attention import _merge, flash_block_with_lse

    b, s, h, d = 1, 256, 2, 64
    q = jax.random.normal(jax.random.PRNGKey(0), (b, s, h, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, 2 * s, h, d), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, 2 * s, h, d), jnp.float32)

    def loss_merged(q_, k_, v_):
        o1, l1 = flash_block_with_lse(q_, k_[:, :s], v_[:, :s], False, True)
        o2, l2 = flash_block_with_lse(q_, k_[:, s:], v_[:, s:], False, True)
        out, _ = _merge(o1.astype(jnp.float32), l1, o2.astype(jnp.float32), l2)
        return jnp.sum(out**2), out

    def loss_ref(q_, k_, v_):
        out = mha_reference(q_, k_, v_, causal=False).astype(jnp.float32)
        return jnp.sum(out**2), out

    (_, om), gm = jax.value_and_grad(loss_merged, argnums=(0, 1, 2),
                                     has_aux=True)(q, k, v)
    (_, orf), gr = jax.value_and_grad(loss_ref, argnums=(0, 1, 2),
                                      has_aux=True)(q, k, v)
    assert float(jnp.max(jnp.abs(om - orf))) < 2e-2
    for name, a, b_ in zip("qkv", gm, gr):
        scale = max(float(np.max(np.abs(np.asarray(b_)))), 1.0)
        assert float(np.max(np.abs(np.asarray(a) - np.asarray(b_)))) / scale \
            < 2e-2, name


def test_ring_balance_report():
    """VERDICT r4 #8: the zigzag load-balance claim as numbers. Per-rank
    block-unit tables from the static chunk-id classification: contiguous
    causal rings pay ~2x the ideal wall (the busiest rank's full block per
    lockstep step while early ranks skip); zigzag pays ~1x (every rank
    computes exactly 2 chunk-units per visit). Total FLOPs are identical."""
    from odh_kubeflow_tpu.ops.ring_attention import ring_balance_report

    for sp in (4, 8):
        cont = ring_balance_report(sp, "contiguous")
        zz = ring_balance_report(sp, "zigzag")
        # same total work in chunk units
        assert sum(cont["per_rank_total_units"]) == sum(zz["per_rank_total_units"])
        # zigzag: every rank does exactly 2 units per visit -> perfectly flat
        assert all(
            u == 2.0 for row in zz["per_rank_units_per_step"] for u in row
        )
        assert abs(zz["balance_ratio"] - 1.0) < 1e-9
        # contiguous: rank r totals r*4 + 2 (strictly increasing -> skewed)
        assert cont["per_rank_total_units"] == [4 * r + 2 for r in range(sp)]
        # exact: wall = 2 + 4(sp-1), ideal = 2sp -> ratio 2 - 3/sp + ...
        assert cont["balance_ratio"] == (2 + 4 * (sp - 1)) / (2 * sp)
        assert cont["balance_ratio"] >= 1.75, cont["balance_ratio"]
        # the headline: zigzag cuts the lockstep wall ~2x at equal FLOPs
        assert (
            zz["lockstep_wall_units"]
            == cont["lockstep_wall_units"] / cont["balance_ratio"]
        )
