"""CPPROFILE=1 control-plane profiler contract tests (ISSUE 20).

The sixth runtime sibling at the RACECHECK/INVCHECK/JAXGUARD/DEPLOYGUARD/
PROFILE bar: inert when disarmed, and when armed its three legs must hold
the invariants the bench ledger's control-plane headlines mine —

- cause chain: every reconcile fired through the real informer -> workqueue
  -> controller path reports the watch event that woke it (kind, verb,
  source object, resourceVersion), keep-first under queue dedup, and
  self-requeues report origin="requeue";
- scan accounting: cache/store list paths report objects-scanned vs
  objects-used, attributed to the reconciling controller, an enclosing
  sweep(...) scope, or the thread's flow — the scheduler's sweeps show up
  under their controller name through a real SimCluster;
- takeover decomposition: the five phases partition the takeover total by
  construction, lease-acquire excludes the standby's healthy wait, and a
  completed takeover emits the manager.takeover trace;
- /debug/reconciles serves snapshots (?controller=/?limit=, bad args = 400),
  incident bundles carry a cpprofile snapshot when armed, flight-recorder
  reconcile samples gain the cause fields;
- the armed per-reconcile hook cost stays under 10% of a real reconcile.
"""
from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from odh_kubeflow_tpu.api.apps import StatefulSet
from odh_kubeflow_tpu.api.core import ConfigMap, Pod
from odh_kubeflow_tpu.api.notebook import Notebook
from odh_kubeflow_tpu.cluster import Client, Store
from odh_kubeflow_tpu.runtime import Manager, Request, Result
from odh_kubeflow_tpu.runtime import cpprofile

pytestmark = pytest.mark.cpprofile


@pytest.fixture(autouse=True)
def _clean_cpprofile(monkeypatch):
    monkeypatch.delenv("CPPROFILE", raising=False)
    cpprofile.reset()
    yield
    cpprofile.reset()


@pytest.fixture
def armed(monkeypatch):
    monkeypatch.setenv("CPPROFILE", "1")


def _spin(seconds: float) -> None:
    """Busy-wait: sleep() under-delivers on loaded CI boxes and the phase
    tests need the time to actually be SPENT."""
    end = time.perf_counter() + seconds
    while time.perf_counter() < end:
        pass


def _wait_for(pred, timeout: float = 5.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


def mk_pod(name, ns="user", labels=None):
    pod = Pod()
    pod.metadata.name = name
    pod.metadata.namespace = ns
    if labels:
        pod.metadata.labels = dict(labels)
    return pod


def mk_nb(name, ns="user"):
    nb = Notebook()
    nb.metadata.name = name
    nb.metadata.namespace = ns
    return nb


# ---------------------------------------------------------------------------
# disarmed inertness
# ---------------------------------------------------------------------------


def test_disarmed_hooks_are_inert():
    assert not cpprofile.enabled()
    cpprofile.stamp_cause("c", "ns/x", kind="Pod", verb="ADDED")
    cpprofile.note_dequeue("c", "ns/x", 0.01)
    cpprofile.note_scan("Pod", 10, 2)
    with cpprofile.sweep("nothing"):
        cpprofile.note_scan("Pod", 10, 2)
    assert cpprofile.reconcile_begin("c", "ns/x") is None
    assert cpprofile.takeover_begin("m", {1}) is None
    assert cpprofile._pending == {}
    assert cpprofile._pending_wait == {}
    assert cpprofile.snapshot() == {
        "enabled": False, "controllers": {}, "sweeps": {}, "takeovers": [],
    }


def test_disarmed_manager_burst_records_nothing():
    store = Store()
    client = Client(store)
    mgr = Manager(store)
    done = threading.Event()
    mgr.builder("inert").for_(Pod).complete(lambda req: done.set() and None)
    mgr.start()
    try:
        client.create(mk_pod("p0"))
        assert done.wait(2)
        mgr.wait_idle()
    finally:
        mgr.stop()
    snap = cpprofile.snapshot()
    assert snap["controllers"] == {} and snap["takeovers"] == []


# ---------------------------------------------------------------------------
# cause chain through the real informer -> workqueue -> controller path
# ---------------------------------------------------------------------------


def test_cause_chain_watch_events(armed):
    store = Store()
    client = Client(store)
    mgr = Manager(store)
    mgr.builder("cause").for_(Pod).complete(lambda req: None)
    mgr.start()
    try:
        client.create(mk_pod("p0"))
        mgr.wait_idle()
        pod = client.get(Pod, "user", "p0")
        pod.metadata.labels = {"touched": "1"}
        client.update(pod)
        mgr.wait_idle()
    finally:
        mgr.stop()
    stats = cpprofile.snapshot()["controllers"]["cause"]
    assert stats["causes"].get("Pod/ADDED", 0) >= 1
    assert stats["causes"].get("Pod/MODIFIED", 0) >= 1
    assert stats["origins"]["watch"] >= 2
    assert stats["reconciles"] == sum(stats["causes"].values())
    by_verb = {s["cause_verb"]: s for s in stats["samples"]}
    added = by_verb["ADDED"]
    assert added["cause_kind"] == "Pod"
    assert added["cause_object"] == "user/p0"
    assert added["cause_rv"] != ""
    assert added["origin"] == "watch"
    assert added["queue_wait_ms"] >= 0.0
    assert added["work_ms"] >= 0.0


def test_owned_event_reports_owned_kind(armed):
    store = Store()
    client = Client(store)
    mgr = Manager(store)
    mgr.builder("owner").for_(Notebook).owns(StatefulSet).complete(
        lambda req: None
    )
    mgr.start()
    try:
        client.create(mk_nb("alpha"))
        mgr.wait_idle()
        nb = client.get(Notebook, "user", "alpha")
        sts = StatefulSet()
        sts.metadata.name = "alpha"
        sts.metadata.namespace = "user"
        sts.set_owner(nb)
        client.create(sts)
        mgr.wait_idle()
    finally:
        mgr.stop()
    stats = cpprofile.snapshot()["controllers"]["owner"]
    assert stats["causes"].get("Notebook/ADDED", 0) >= 1
    assert stats["causes"].get("StatefulSet/ADDED", 0) >= 1


def test_self_requeue_reports_requeue_origin(armed):
    store = Store()
    client = Client(store)
    mgr = Manager(store)
    calls = []
    done = threading.Event()

    def reconcile(req: Request):
        calls.append(req.key)
        if len(calls) == 1:
            return Result(requeue_after=0.02)
        done.set()
        return None

    mgr.builder("requeuer").for_(ConfigMap).complete(reconcile)
    mgr.start()
    try:
        cm = ConfigMap()
        cm.metadata.name = "cfg"
        cm.metadata.namespace = "user"
        client.create(cm)
        assert done.wait(3)
        mgr.wait_idle()
    finally:
        mgr.stop()
    stats = cpprofile.snapshot()["controllers"]["requeuer"]
    assert stats["origins"]["requeue"] >= 1
    assert stats["causes"].get("self/requeue", 0) >= 1
    requeued = [s for s in stats["samples"] if s["origin"] == "requeue"]
    assert requeued and requeued[0]["cause_kind"] == "self"


def test_keep_first_cause_matches_queue_dedup(armed):
    """The queue drops a second add of a queued key; the cause map must
    keep the FIRST stamp for the same reason."""
    cpprofile.stamp_cause("c", "ns/x", kind="Pod", verb="ADDED",
                          obj={"metadata": {"name": "x", "namespace": "ns",
                                            "resourceVersion": "1"}})
    cpprofile.stamp_cause("c", "ns/x", kind="Pod", verb="MODIFIED",
                          obj={"metadata": {"name": "x", "namespace": "ns",
                                            "resourceVersion": "2"}})
    ctx = cpprofile.reconcile_begin("c", "ns/x")
    assert ctx["cause"]["verb"] == "ADDED" and ctx["cause"]["rv"] == "1"
    cpprofile.reconcile_end(ctx, outcome="ok")
    # consumed: the next begin on the same key has no cause -> requeue
    ctx2 = cpprofile.reconcile_begin("c", "ns/x")
    assert ctx2["cause"] is None
    cpprofile.reconcile_end(ctx2)
    stats = cpprofile.snapshot()["controllers"]["c"]
    assert stats["causes"] == {"Pod/ADDED": 1, "self/requeue": 1}


# ---------------------------------------------------------------------------
# scan accounting
# ---------------------------------------------------------------------------


def test_reconcile_scan_accounting(armed):
    store = Store()
    client = Client(store)
    mgr = Manager(store)
    listed = []

    def reconcile(req: Request):
        pods = mgr.client.list(Pod, namespace="user", labels={"app": "keep"})
        listed.append(len(pods))
        return None

    mgr.builder("scanner").for_(Notebook).complete(reconcile)
    mgr.start()
    try:
        for i in range(4):
            client.create(mk_pod(f"noise-{i}", labels={"app": "noise"}))
        client.create(mk_pod("keep-0", labels={"app": "keep"}))
        client.create(mk_nb("nb"))
        mgr.wait_idle()
    finally:
        mgr.stop()
    assert listed and listed[-1] == 1
    stats = cpprofile.snapshot()["controllers"]["scanner"]
    assert stats["scan_calls"] >= 1
    # the flat-cache cost: 5 pods examined to yield 1 match
    assert stats["scanned"] >= 5
    assert stats["used"] < stats["scanned"]
    assert stats["scans_per_reconcile"] > 0
    sample = stats["samples"][-1]
    assert sample["scanned"] >= 5 and sample["used"] >= 1


def test_sweep_scope_attributes_off_worker_scans(armed):
    store = Store()
    client = Client(store)
    for i in range(3):
        client.create(mk_pod(f"p{i}"))
    with cpprofile.sweep("test-sweep"):
        client.list(Pod, namespace="user")
    sweeps = cpprofile.snapshot()["sweeps"]
    assert sweeps["test-sweep"]["scan_calls"] >= 1
    assert sweeps["test-sweep"]["scanned"] >= 3


def test_scheduler_sweep_scan_accounting(armed):
    """A real SimCluster pass: the scheduler's reconciles read node/pod
    state through the hooked store paths and must show up attributed to
    the 'scheduler' controller."""
    from odh_kubeflow_tpu.cluster import SimCluster
    from odh_kubeflow_tpu.api.core import Container

    c = SimCluster()
    c.start()
    try:
        c.add_cpu_pool("default-pool", nodes=2)
        sts = StatefulSet()
        sts.metadata.name = "web"
        sts.metadata.namespace = "user"
        sts.spec.replicas = 2
        sts.spec.service_name = "web"
        sts.spec.selector.match_labels = {"app": "web"}
        sts.spec.template.metadata.labels = {"app": "web"}
        sts.spec.template.spec.containers = [Container(name="web", image="img:1")]
        c.client.create(sts)
        assert _wait_for(
            lambda: all(
                p.spec.node_name
                for p in c.client.list(Pod, namespace="user")
            ) and len(c.client.list(Pod, namespace="user")) == 2,
            timeout=10,
        )
        c.wait_idle()
    finally:
        c.stop()
    controllers = cpprofile.snapshot()["controllers"]
    assert "scheduler" in controllers
    sched = controllers["scheduler"]
    assert sched["reconciles"] >= 2
    assert sched["scan_calls"] >= 1 and sched["scanned"] >= 1
    # scheduling was caused by pod watch events, not self-requeues
    assert any(k.startswith("Pod/") for k in sched["causes"])


# ---------------------------------------------------------------------------
# takeover decomposition
# ---------------------------------------------------------------------------


def test_takeover_phases_partition_total(armed):
    store = Store()
    client = Client(store)
    mgr = Manager(store)
    wrote = []

    def reconcile(req: Request):
        if not wrote:
            cm = mgr.client.get(ConfigMap, req.namespace, req.name)
            cm.metadata.labels = {"written": "1"}
            mgr.client.update(cm)
            wrote.append(req.key)
        return None

    mgr.builder("writer").for_(ConfigMap).complete(reconcile)
    mgr.start()
    try:
        cm = ConfigMap()
        cm.metadata.name = "cfg"
        cm.metadata.namespace = "user"
        client.create(cm)
        assert _wait_for(
            lambda: any(
                t.get("complete") for t in cpprofile.snapshot()["takeovers"]
            ),
            timeout=5,
        ), "takeover never completed"
        mgr.wait_idle()
    finally:
        mgr.stop()
    done = [t for t in cpprofile.snapshot()["takeovers"] if t.get("complete")]
    assert len(done) == 1
    t = done[0]
    assert set(t["phases"]) == set(cpprofile.TAKEOVER_PHASES)
    assert all(v >= 0.0 for v in t["phases"].values())
    # the running-max construction makes the phases PARTITION the total
    assert abs(sum(t["phases"].values()) - t["total_s"]) < 1e-5
    assert 0.0 <= t["relist_share"] <= 1.0
    # one connected trace: root + a child per phase
    from odh_kubeflow_tpu.utils import tracing

    roots = tracing.recent_spans(name="manager.takeover")
    assert roots, "manager.takeover trace root missing"
    root = roots[-1]
    children = [
        s for s in tracing.recent_spans(trace_id=root["trace_id"])
        if s["name"].startswith("takeover.")
    ]
    assert {s["name"] for s in children} == {
        f"takeover.{p}" for p in cpprofile.TAKEOVER_PHASES
    }
    # the histogram family observed each phase
    from odh_kubeflow_tpu.runtime.metrics import global_registry

    assert 'cp_takeover_phase_seconds_bucket{phase="relist"' in (
        global_registry.render()
    )


def test_lease_acquire_excludes_healthy_wait(armed):
    """touch_waiting restamps the clock on every failed leadership poll:
    a standby that waited 10ms before winning must not bill that wait to
    lease-acquire."""
    tr = cpprofile.takeover_begin("standby", {1})
    _spin(0.01)
    tr.touch_waiting()  # last failed poll before the lease lands
    tr.mark("leader")
    assert tr._segments()["lease-acquire"] < 0.008
    # after the first mark, touch_waiting is a no-op (takeover underway)
    t0 = tr.t0
    tr.touch_waiting()
    assert tr.t0 == t0
    tr.abandon()
    takeovers = cpprofile.snapshot()["takeovers"]
    assert takeovers and takeovers[-1]["complete"] is False
    assert takeovers[-1]["phases"]["lease-acquire"] < 0.008


# ---------------------------------------------------------------------------
# /debug/reconciles + incident bundles + recorder samples
# ---------------------------------------------------------------------------


class _StubManager:
    """The minimum surface ServingEndpoints asks of a manager."""

    def __init__(self):
        from odh_kubeflow_tpu.runtime.metrics import Registry

        self.metrics = Registry()

    def healthz(self) -> bool:
        return True

    def readyz(self) -> bool:
        return True


@pytest.fixture
def endpoints():
    from odh_kubeflow_tpu.runtime.serving import ServingEndpoints

    ep = ServingEndpoints(
        _StubManager(), metrics_port=0, health_port=0, host="127.0.0.1"
    ).start()
    yield ep
    ep.stop()


def _get(ep, path):
    host, port = ep.metrics_address
    with urllib.request.urlopen(f"http://{host}:{port}{path}", timeout=5) as r:
        return r.status, json.loads(r.read())


def _unit_reconcile(controller: str, key: str = "ns/a") -> None:
    cpprofile.stamp_cause(controller, key, kind="Pod", verb="ADDED",
                          obj={"metadata": {"name": "a", "namespace": "ns",
                                            "resourceVersion": "7"}})
    ctx = cpprofile.reconcile_begin(controller, key)
    cpprofile.note_scan("Pod", 10, 2)
    cpprofile.reconcile_end(ctx, outcome="ok")


def test_debug_reconciles_serves_snapshot(armed, endpoints):
    _unit_reconcile("alpha")
    _unit_reconcile("beta")
    status, payload = _get(endpoints, "/debug/reconciles")
    assert status == 200
    assert payload["enabled"] is True
    assert set(payload["controllers"]) == {"alpha", "beta"}
    assert payload["controllers"]["alpha"]["causes"] == {"Pod/ADDED": 1}
    # ?controller= narrows, ?limit= truncates the sample rows
    status, payload = _get(endpoints, "/debug/reconciles?controller=alpha")
    assert status == 200 and set(payload["controllers"]) == {"alpha"}
    status, payload = _get(endpoints, "/debug/reconciles?limit=0")
    assert status == 200
    assert payload["controllers"]["alpha"]["samples"] == []


def test_debug_reconciles_bad_args_are_400(armed, endpoints):
    _unit_reconcile("alpha")
    host, port = endpoints.metrics_address
    for query in ("?limit=nope", "?limit=-1", "?controller=typo"):
        with pytest.raises(urllib.error.HTTPError) as excinfo:
            urllib.request.urlopen(
                f"http://{host}:{port}/debug/reconciles{query}", timeout=5
            )
        assert excinfo.value.code == 400


def test_debug_index_links_reconciles(endpoints):
    host, port = endpoints.metrics_address
    with urllib.request.urlopen(f"http://{host}:{port}/debug/", timeout=5) as r:
        assert "/debug/reconciles" in r.read().decode()


def test_incident_bundle_carries_cpprofile(armed, monkeypatch):
    from odh_kubeflow_tpu.runtime.flightrecorder import FlightRecorder

    _unit_reconcile("bundled")
    rec = FlightRecorder()
    bundle_id = rec.snapshot("cpprofile-test", subject="armed")
    bundle = rec.get(bundle_id)
    assert "bundled" in bundle["cpprofile"]["controllers"]
    # disarmed: the freeze block is skipped entirely
    monkeypatch.delenv("CPPROFILE")
    bundle_id = rec.snapshot("cpprofile-test", subject="disarmed")
    assert "cpprofile" not in rec.get(bundle_id)


def test_recorder_reconcile_samples_gain_cause_fields(armed):
    """Satellite 1: the flight recorder's always-on per-reconcile samples
    carry cause_kind/cause_verb/queue_wait_ms when CPPROFILE is armed."""
    from odh_kubeflow_tpu.runtime.flightrecorder import recorder

    store = Store()
    client = Client(store)
    mgr = Manager(store)
    mgr.builder("recorded").for_(Pod).complete(lambda req: None)
    mgr.start()
    try:
        client.create(mk_pod("p0"))
        mgr.wait_idle()
    finally:
        mgr.stop()
    samples = [
        r for r in recorder.records("reconcile")
        if r.get("controller") == "recorded"
    ]
    assert samples
    assert samples[-1]["cause_kind"] == "Pod"
    assert samples[-1]["cause_verb"] == "ADDED"
    assert samples[-1]["queue_wait_ms"] >= 0.0


# ---------------------------------------------------------------------------
# overhead + bucket hygiene + reset
# ---------------------------------------------------------------------------


def test_armed_overhead_under_ten_percent_per_reconcile(monkeypatch):
    """The acceptance bar: the full armed hook chain (stamp -> dequeue ->
    begin -> scan -> end) must cost <10% of a real reconcile body (one
    store-backed list over a 20-object namespace)."""
    store = Store()
    client = Client(store)
    for i in range(20):
        client.create(mk_pod(f"p{i}"))

    n = 300

    def body_cost():
        t0 = time.perf_counter()
        for _ in range(n):
            client.list(Pod, namespace="user")
        return (time.perf_counter() - t0) / n

    recon_s = min(body_cost() for _ in range(3))

    monkeypatch.setenv("CPPROFILE", "1")
    obj = {"metadata": {"name": "k", "namespace": "ns", "resourceVersion": "1"}}

    def hook_cost():
        t0 = time.perf_counter()
        for _ in range(n):
            cpprofile.stamp_cause("ovh", "ns/k", kind="Pod", verb="MODIFIED",
                                  obj=obj)
            cpprofile.note_dequeue("ovh", "ns/k", 0.001)
            ctx = cpprofile.reconcile_begin("ovh", "ns/k")
            cpprofile.note_scan("Pod", 20, 1)
            cpprofile.reconcile_end(ctx, outcome="ok")
        return (time.perf_counter() - t0) / n

    per_hook = min(hook_cost() for _ in range(3))
    # same absolute-floor idiom as the profiler/jaxguard overhead tests:
    # 10% of a measured reconcile, floored to absorb CI scheduler noise
    assert per_hook < max(0.10 * recon_s, 0.0005), (
        f"cpprofile hooks cost {per_hook * 1e6:.1f}us against a "
        f"{recon_s * 1e6:.1f}us reconcile"
    )


def test_histogram_ranges_declared_with_subms_buckets():
    """Satellite 2: the sub-ms bucket audit — sim reconciles land in tens
    of microseconds, so both the cp_* families and the pre-existing queue/
    reconcile histograms need sub-ms resolution, declared in
    HISTOGRAM_RANGES so the bucket lint covers them."""
    from odh_kubeflow_tpu.analysis.metric_rules import HISTOGRAM_RANGES
    from odh_kubeflow_tpu.runtime.metrics import _QUEUE_BUCKETS

    for family in ("cp_queue_wait_seconds", "cp_reconcile_work_seconds",
                   "cp_takeover_phase_seconds"):
        assert family in HISTOGRAM_RANGES, family
    # the audited families resolve sub-ms: >= 3 boundaries under 1ms
    assert sum(1 for b in cpprofile.CP_WAIT_BUCKETS if b < 0.001) >= 3
    assert sum(1 for b in _QUEUE_BUCKETS if b < 0.001) >= 3
    lo, _hi = HISTOGRAM_RANGES["workqueue_queue_duration_seconds"]
    assert lo <= _QUEUE_BUCKETS[0]


def test_reset_clears_aggregates(armed):
    _unit_reconcile("gone")
    with cpprofile.sweep("gone-sweep"):
        cpprofile.note_scan("Pod", 5, 1)
    tr = cpprofile.takeover_begin("gone-mgr", {1})
    cpprofile.reset()
    snap = cpprofile.snapshot()
    assert snap["controllers"] == {} and snap["sweeps"] == {}
    assert snap["takeovers"] == []
    tr.abandon()  # a stale tracker after reset must not resurrect state
    assert cpprofile.snapshot()["takeovers"] == []
