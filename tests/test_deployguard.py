"""DEPLOYGUARD runtime-twin contract tests (ISSUE 14).

The static deploylint pass proves the SOURCE stays inside the declared
deployment surface; these tests prove the PROCESS guard catches the same
drift live — and that it costs nothing when disarmed:

- a manager-flow request exceeding the declared RBAC raises RBACDriftError
  AT the offending call, naming the flow, verb and kind;
- traffic inside the declared surface passes and is recorded;
- the two flow-identity invariants hold: the leader-election flow carries
  only Lease traffic, and Lease traffic never rides a controller flow (the
  misattributed-lease-write regression — exactly the failover drift the
  armed loadtest lanes turn into a hard failure);
- non-manager flows (sim actors, drivers, bare test clients) are
  record-only, never enforced;
- the surface artifact round-trips: dump -> JSON -> the rbac-coverage
  checker's --deploy-surface input, merging across processes;
- the per-call audit stays under 10% overhead armed and the whole module
  is inert with DEPLOYGUARD unset (same bar as the invcheck/jaxguard
  overhead tests).
"""
import json
import time

import pytest

from odh_kubeflow_tpu.analysis import deploysurface as ds
from odh_kubeflow_tpu.analysis.checkers.deploylint import RbacCoverageChecker
from odh_kubeflow_tpu.api.coordination import Lease
from odh_kubeflow_tpu.api.core import ConfigMap, Namespace
from odh_kubeflow_tpu.cluster import Client, Store
from odh_kubeflow_tpu.cluster.flowcontrol import (
    LEADER_ELECTION_FLOW,
    flow_context,
)
from odh_kubeflow_tpu.utils import deployguard

pytestmark = [pytest.mark.analysis, pytest.mark.deploylint]

NS = "deployguard"


@pytest.fixture
def armed():
    deployguard.disarm()
    guard = deployguard.arm()
    yield guard
    deployguard.disarm()


def mk_cm(name: str) -> ConfigMap:
    cm = ConfigMap()
    cm.metadata.namespace = NS
    cm.metadata.name = name
    return cm


def mk_lease(name: str) -> Lease:
    lease = Lease()
    lease.metadata.namespace = "kube-system"
    lease.metadata.name = name
    return lease


# ---------------------------------------------------------------------------
# enforcement on manager flows
# ---------------------------------------------------------------------------

def test_granted_surface_passes_and_is_recorded(armed):
    client = Client(Store())
    with flow_context("notebook"):
        client.create(mk_cm("green"))
        client.get(ConfigMap, NS, "green")
        client.list(ConfigMap, namespace=NS)
    assert ("notebook", "create", "ConfigMap", "") in armed.surface
    assert ("notebook", "get", "ConfigMap", "") in armed.surface
    assert ("notebook", "list", "ConfigMap", "") in armed.surface
    assert armed.drifts == 0


def test_ungranted_verb_raises_at_the_offending_call(armed):
    client = Client(Store())
    with flow_context("notebook"):
        with pytest.raises(deployguard.RBACDriftError) as ei:
            client.create(_ns("drift"))
    msg = str(ei.value)
    # the error names flow, verb and kind — enough to find the call without
    # a debugger
    assert "notebook" in msg and "create" in msg and "Namespace" in msg
    assert armed.drifts == 1
    # the attempt is still part of the recorded surface (the artifact must
    # show what the process TRIED, drift included)
    assert ("notebook", "create", "Namespace", "") in armed.surface


def _ns(name: str) -> Namespace:
    ns = Namespace()
    ns.metadata.name = name
    return ns


def test_non_manager_flows_are_record_only(armed):
    """Sim actors and bare test clients carry their own identities — their
    traffic never counts against the manager's RBAC."""
    client = Client(Store())
    # no flow_context at all: the anonymous default flow
    client.create(_ns("anonymous"))
    with flow_context("kubelet"):
        client.create(_ns("sim-actor"))
    assert armed.drifts == 0
    assert ("", "create", "Namespace", "") in armed.surface
    assert ("kubelet", "create", "Namespace", "") in armed.surface


# ---------------------------------------------------------------------------
# flow-identity invariants (the misattributed-lease regression)
# ---------------------------------------------------------------------------

def test_lease_write_on_controller_flow_is_a_hard_failure(armed):
    """The shard-failover drift the armed loadtest lanes exist to catch: a
    lease write attributed to a workload flow would contend in the workload
    budget and dodge the write fence. DEPLOYGUARD fails it at the call."""
    client = Client(Store())
    with flow_context("notebook"):
        with pytest.raises(deployguard.RBACDriftError, match="Lease"):
            client.create(mk_lease("misattributed"))
    assert armed.drifts == 1


def test_elector_client_lease_traffic_passes(armed):
    """The legitimate path: the elector's own client pins the exempt flow."""
    elector_client = Client(Store())
    elector_client.flow = LEADER_ELECTION_FLOW
    lease = elector_client.create(mk_lease("held"))
    lease.spec.holder_identity = "mgr-0"
    elector_client.update(lease)
    assert armed.drifts == 0
    assert (LEADER_ELECTION_FLOW, "create", "Lease", "") in armed.surface


def test_leader_election_flow_may_only_carry_lease_traffic(armed):
    elector_client = Client(Store())
    elector_client.flow = LEADER_ELECTION_FLOW
    with pytest.raises(deployguard.RBACDriftError, match="only"):
        elector_client.create(mk_cm("smuggled"))
    assert armed.drifts == 1


# ---------------------------------------------------------------------------
# disarmed: inert
# ---------------------------------------------------------------------------

def test_disarmed_client_is_inert(monkeypatch):
    monkeypatch.delenv("DEPLOYGUARD", raising=False)
    deployguard.disarm()
    assert deployguard.ACTIVE is None
    client = Client(Store())
    with flow_context("notebook"):
        client.create(_ns("off"))  # would drift armed; passes disarmed
    assert deployguard.ACTIVE is None


def test_enabled_parses_like_the_sibling_guards(monkeypatch):
    for value, want in (("", False), ("0", False), ("false", False),
                        ("1", True), ("true", True)):
        monkeypatch.setenv("DEPLOYGUARD", value)
        assert deployguard.enabled() is want
    monkeypatch.delenv("DEPLOYGUARD")
    assert deployguard.enabled() is False


# ---------------------------------------------------------------------------
# the surface artifact
# ---------------------------------------------------------------------------

def test_surface_artifact_round_trips_into_the_checker(armed, tmp_path):
    client = Client(Store())
    with flow_context("notebook"):
        client.create(mk_cm("dumped"))
    out = tmp_path / "surface.json"
    armed.dump(str(out))
    surface = ds.surface_tuples_from_artifact(json.loads(out.read_text()))
    assert ("notebook", "create", "ConfigMap", "") in surface
    assert ("", "configmaps") in ds.exercised_resources_from_surface(surface)
    # the checker consumes exactly this shape (cli.py --deploy-surface)
    checker = RbacCoverageChecker()
    checker.surface = surface
    assert checker.surface


def test_surface_dump_merges_across_processes(armed, tmp_path):
    """faults.sh lanes run several pytest processes against one artifact
    path — a later dump must union with, not clobber, the earlier one."""
    out = tmp_path / "surface.json"
    armed.surface.add(("notebook", "get", "ConfigMap", ""))
    armed.dump(str(out))
    second = deployguard.Guard()
    second.surface.add(("tpu-job", "update_status", "TPUJob", "status"))
    second.dump(str(out))
    merged = ds.surface_tuples_from_artifact(json.loads(out.read_text()))
    assert ("notebook", "get", "ConfigMap", "") in merged
    assert ("tpu-job", "update_status", "TPUJob", "status") in merged


def test_update_status_maps_to_the_status_subresource(armed):
    client = Client(Store())
    with flow_context("notebook"):
        cm = client.create(mk_cm("sub"))
        client.update(cm)
    assert ("notebook", "update", "ConfigMap", "") in armed.surface
    # the mapping table, not the client, owns the subresource attribution
    assert ds.CLIENT_VERBS["update_status"] == ("update", "status")
    assert ds.required_rbac("update_status", "Notebook") == (
        "kubeflow.org", "notebooks/status", "update",
    )


# ---------------------------------------------------------------------------
# overhead
# ---------------------------------------------------------------------------

def test_armed_observe_overhead_under_ten_percent(armed):
    store = Store()
    client = Client(store)
    with flow_context("notebook"):
        client.create(mk_cm("bench"))
    n = 200

    def run():
        t0 = time.perf_counter()
        for _ in range(n):
            client.get(ConfigMap, NS, "bench")
        return (time.perf_counter() - t0) / n

    with flow_context("notebook"):
        armed_cost = min(run() for _ in range(3))
    deployguard.disarm()
    with flow_context("notebook"):
        base = min(run() for _ in range(3))
    added = armed_cost - base
    # same bar as the invcheck/jaxguard overhead tests: 10% or an absolute
    # floor that absorbs scheduler noise on a loaded CI box
    assert added < max(0.10 * base, 0.0005), (
        f"observe adds {added * 1e6:.1f}us/call over {base * 1e6:.1f}us"
    )
