"""Unit tests for the from-scratch API machinery (serde, meta, labels, patch)."""
import copy

from odh_kubeflow_tpu.apimachinery import (
    Condition,
    LabelSelector,
    LabelSelectorRequirement,
    json_merge_patch,
    match_labels,
    sanitize_name,
    set_condition,
)
from odh_kubeflow_tpu.api.core import Container, EnvVar, Pod, PodSpec, Probe, Service
from odh_kubeflow_tpu.api.notebook import Notebook, TPUSpec
from odh_kubeflow_tpu.apimachinery.scheme import default_scheme


def test_roundtrip_camel_case():
    pod = Pod(api_version="v1", kind="Pod")
    pod.metadata.name = "nb-0"
    pod.metadata.namespace = "user-ns"
    pod.metadata.labels = {"notebook-name": "nb"}
    pod.spec.containers.append(
        Container(name="nb", image="img:1", env=[EnvVar(name="NB_PREFIX", value="/x")])
    )
    d = pod.to_dict()
    assert d["metadata"]["name"] == "nb-0"
    assert d["spec"]["containers"][0]["env"][0] == {"name": "NB_PREFIX", "value": "/x"}
    back = Pod.from_dict(d)
    assert back.spec.containers[0].env[0].value == "/x"
    assert back.metadata.labels == {"notebook-name": "nb"}


def test_omitempty():
    svc = Service(api_version="v1", kind="Service")
    svc.metadata.name = "s"
    d = svc.to_dict()
    assert "labels" not in d["metadata"]
    assert "status" not in d  # empty dict field omitted (Go map omitempty)
    assert d["spec"] == {}  # struct fields always emitted (Go struct semantics)


def test_optional_int_zero_survives():
    from odh_kubeflow_tpu.api.apps import StatefulSet

    sts = StatefulSet()
    sts.spec.replicas = 0
    d = sts.to_dict()
    assert d["spec"]["replicas"] == 0
    back = StatefulSet.from_dict(d)
    assert back.spec.replicas == 0
    sts.spec.replicas = None
    assert "replicas" not in sts.to_dict()["spec"]


def test_required_empty_selector_survives():
    from odh_kubeflow_tpu.api.networking import NetworkPolicy

    np = NetworkPolicy()
    d = np.to_dict()
    assert d["spec"]["podSelector"] == {}  # select-all must not vanish


def test_scheme_hub_gvk_stable():
    gvk = default_scheme.gvk_for(Notebook)
    assert gvk.api_version == "kubeflow.org/v1beta1"


def test_owner_refs():
    from odh_kubeflow_tpu.api.apps import StatefulSet

    nb = Notebook(api_version="kubeflow.org/v1beta1", kind="Notebook")
    nb.metadata.name = "nb"
    nb.metadata.uid = "u1"
    other = Notebook(api_version="kubeflow.org/v1beta1", kind="Notebook")
    other.metadata.name = "other"
    other.metadata.uid = "u2"
    sts = StatefulSet(api_version="apps/v1", kind="StatefulSet")
    sts.set_owner(nb)
    sts.set_owner(other, controller=False)
    # non-controller add must not evict the controller ref
    assert any(r.controller for r in sts.metadata.owner_references)
    assert len(sts.metadata.owner_references) == 2
    assert sts.owned_by(nb) and sts.owned_by(other)
    # empty-uid objects never match an owner with a different identity
    sts2 = StatefulSet(api_version="apps/v1", kind="StatefulSet")
    assert not sts2.owned_by(nb)


def test_unknown_fields_roundtrip():
    d = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": "p"},
        "spec": {"containers": [], "futureField": {"x": 1}},
    }
    pod = Pod.from_dict(d)
    out = pod.to_dict()
    assert out["spec"]["futureField"] == {"x": 1}


def test_probe_exec_json_key():
    p = Probe(exec_={"command": ["true"]})
    assert p.to_dict() == {"exec": {"command": ["true"]}}
    assert Probe.from_dict({"exec": {"command": ["x"]}}).exec_ == {"command": ["x"]}


def test_notebook_tpu_block_roundtrip():
    nb = Notebook(api_version="kubeflow.org/v1beta1", kind="Notebook")
    nb.metadata.name = "trainer"
    nb.spec.tpu = TPUSpec(accelerator="v5p", topology="2x2x4")
    nb.spec.template.spec.containers.append(Container(name="trainer", image="jax:latest"))
    d = nb.to_dict()
    assert d["spec"]["tpu"] == {"accelerator": "v5p", "topology": "2x2x4"}
    back = default_scheme.decode(d)
    assert isinstance(back, Notebook)
    assert back.spec.tpu.accelerator == "v5p"


def test_reference_shaped_manifest_parses():
    # A CR written for the reference controller (no tpu block) parses unchanged.
    d = {
        "apiVersion": "kubeflow.org/v1beta1",
        "kind": "Notebook",
        "metadata": {"name": "jupyter", "namespace": "kubeflow"},
        "spec": {
            "template": {
                "spec": {
                    "containers": [
                        {
                            "name": "jupyter",
                            "image": "jupyter/minimal",
                            "resources": {"requests": {"cpu": "500m"}},
                        }
                    ]
                }
            }
        },
    }
    nb = Notebook.from_dict(d)
    assert nb.spec.tpu is None
    assert nb.spec.template.spec.containers[0].resources.requests["cpu"] == "500m"


def test_label_selector():
    sel = LabelSelector(
        match_labels={"app": "nb"},
        match_expressions=[
            LabelSelectorRequirement(key="tier", operator="In", values=["gold"])
        ],
    )
    assert sel.matches({"app": "nb", "tier": "gold"})
    assert not sel.matches({"app": "nb", "tier": "silver"})
    assert not sel.matches({"tier": "gold"})
    assert match_labels({"a": "1"}, {"a": "1", "b": "2"})
    assert not match_labels({"a": "1"}, {"b": "2"})


def test_json_merge_patch_deletes_annotation():
    obj = {"metadata": {"annotations": {"kubeflow-resource-stopped": "lock", "keep": "1"}}}
    out = json_merge_patch(obj, {"metadata": {"annotations": {"kubeflow-resource-stopped": None}}})
    assert out["metadata"]["annotations"] == {"keep": "1"}
    # original untouched
    assert "kubeflow-resource-stopped" in obj["metadata"]["annotations"]


def test_set_condition_preserves_transition_time():
    conds = set_condition([], Condition(type="Ready", status="True"))
    t0 = conds[0].last_transition_time
    conds = set_condition(conds, Condition(type="Ready", status="True", reason="r2"))
    assert conds[0].last_transition_time == t0
    assert conds[0].reason == "r2"
    conds = set_condition(conds, Condition(type="Ready", status="False"))
    assert len(conds) == 1


def test_sanitize_name_long():
    long = "a" * 80
    s = sanitize_name(long)
    assert len(s) <= 63
    assert s != sanitize_name("b" * 80)


# ---- REST mapper + RFC 6902 JSON Patch (transport foundations) ----


def test_rest_mapper_paths():
    from odh_kubeflow_tpu.apimachinery import RESTMapper

    m = RESTMapper()
    nb = m.mapping_for("kubeflow.org/v1beta1", "Notebook")
    assert nb.plural == "notebooks"
    assert nb.path("user-ns", "my-nb") == (
        "/apis/kubeflow.org/v1beta1/namespaces/user-ns/notebooks/my-nb"
    )
    assert nb.path("user-ns", "my-nb", "status").endswith("/my-nb/status")
    cm = m.mapping_for("v1", "ConfigMap")
    assert cm.path("ns") == "/api/v1/namespaces/ns/configmaps"
    crb = m.mapping_for("rbac.authorization.k8s.io/v1", "ClusterRoleBinding")
    assert not crb.namespaced
    assert crb.path(namespace="ignored", name="x") == (
        "/apis/rbac.authorization.k8s.io/v1/clusterrolebindings/x"
    )
    np = m.mapping_for("networking.k8s.io/v1", "NetworkPolicy")
    assert np.plural == "networkpolicies"
    assert m.kind_for("v1", "configmaps") == ("v1", "ConfigMap")


def test_json_patch_apply_roundtrip():
    from odh_kubeflow_tpu.apimachinery import json_patch_apply, json_patch_diff

    old = {
        "metadata": {"name": "nb", "annotations": {"a": "1", "drop": "x"}},
        "spec": {"containers": [{"name": "c", "image": "i:1"}], "extra": True},
    }
    new = {
        "metadata": {"name": "nb", "annotations": {"a": "2", "added": "y"}},
        "spec": {"containers": [{"name": "c", "image": "i:2"}, {"name": "s"}]},
    }
    ops = json_patch_diff(old, new)
    assert json_patch_apply(old, ops) == new
    # no-op diff is empty
    assert json_patch_diff(new, new) == []


def test_json_patch_pointer_escaping():
    from odh_kubeflow_tpu.apimachinery import json_patch_apply, json_patch_diff

    old = {"metadata": {"annotations": {}}}
    new = {"metadata": {"annotations": {"kubeflow.org/last-activity": "t", "a/b~c": "v"}}}
    ops = json_patch_diff(old, new)
    assert json_patch_apply(old, ops) == new


def test_json_patch_ops():
    from odh_kubeflow_tpu.apimachinery import json_patch_apply

    doc = {"a": [1, 2], "b": {"c": 1}}
    out = json_patch_apply(
        doc,
        [
            {"op": "add", "path": "/a/-", "value": 3},
            {"op": "test", "path": "/b/c", "value": 1},
            {"op": "move", "from": "/b/c", "path": "/d"},
            {"op": "copy", "from": "/a/0", "path": "/e"},
            {"op": "remove", "path": "/a/1"},
            {"op": "replace", "path": "/e", "value": 9},
        ],
    )
    assert out == {"a": [1, 3], "b": {}, "d": 1, "e": 9}


def test_rest_mapper_populate_from_scheme():
    from odh_kubeflow_tpu.apimachinery import RESTMapper, default_scheme
    import odh_kubeflow_tpu.api  # noqa: F401 — triggers registrations

    m = RESTMapper()
    m.populate_from_scheme(default_scheme)
    assert m.kind_for("kubeflow.org/v1beta1", "notebooks") == (
        "kubeflow.org/v1beta1",
        "Notebook",
    )
    assert m.kind_for("apps/v1", "statefulsets") == ("apps/v1", "StatefulSet")
