"""Slice repair: the accelerator layer fails (host preemption, dead chips,
degraded ICI) and the operator heals the slice end-to-end — Degraded ->
checkpoint-before-evict -> gang rescheduled all-or-nothing (falling back to a
different pool of the same topology) -> Ready again, with MTTR telemetry and
a `slice.repair` trace; capacity that never recovers ends in an explicit
terminal RepairFailed event, never a silently stuck notebook.

Deterministic tier-1 tests (marker: slice_repair); the seeded soak at the
bottom is the acceptance gate ci/faults.sh reruns under its stress loop.
"""
import time

import pytest

from odh_kubeflow_tpu.api.core import Container, Event, Node, Pod
from odh_kubeflow_tpu.api.notebook import Notebook, TPUSpec
from odh_kubeflow_tpu.cluster import SimCluster, seeded_slice_bad_day
from odh_kubeflow_tpu.cluster.faults import PREEMPTION_TAINT_KEY
from odh_kubeflow_tpu.controllers import (
    Config,
    NotebookReconciler,
    ProbeStatusController,
    SliceRepairController,
    constants as C,
)
from odh_kubeflow_tpu.probe import sim_agent_behavior
from odh_kubeflow_tpu.runtime import Manager
from odh_kubeflow_tpu.runtime.flightrecorder import recorder
from odh_kubeflow_tpu.tpu import GKE_NODEPOOL_LABEL, telemetry
from odh_kubeflow_tpu.utils import tracing

pytestmark = pytest.mark.slice_repair

NS = "repair"

FAST = Config(
    readiness_probe_period_s=0.15,
    checkpoint_window_s=1.0,
    repair_max_attempts=4,
    repair_backoff_s=0.3,
    repair_backoff_max_s=1.0,
)


@pytest.fixture()
def env():
    cluster = SimCluster().start()
    # two v5p pools of the SAME topology (2x2x2 = 2 hosts each): the repair
    # fallback pool. Plus v5e singles for the device-fault tests.
    cluster.add_tpu_pool("v5p", "v5p", "2x2x2", slices=2)
    cluster.add_tpu_pool("v5e", "v5e", "2x2", slices=3)
    mgr = Manager(cluster.store)
    NotebookReconciler(mgr, FAST).setup()
    ProbeStatusController(mgr, FAST, http_get=cluster.http_get).setup()
    repair = SliceRepairController(mgr, FAST, http_get=cluster.http_get)
    repair.unreachable_dwell_s = 0.6
    repair.setup()
    agents = {}
    cluster.add_pod_behavior(
        sim_agent_behavior(agents, duty=0.9, kernels_busy=True)
    )
    mgr.start()
    yield cluster, mgr, agents, repair
    mgr.stop()
    cluster.stop()
    cluster.faults.clear()


def mk_nb(name, accelerator="v5p", topology="2x2x2"):
    nb = Notebook()
    nb.metadata.name = name
    nb.metadata.namespace = NS
    nb.spec.template.spec.containers = [Container(name=name, image="jax:1")]
    nb.spec.tpu = TPUSpec(accelerator=accelerator, topology=topology)
    return nb


def wait_for(fn, timeout=30, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(0.05)
    raise AssertionError(f"timeout: {msg}")


def get_nb(cluster, name):
    return cluster.client.get(Notebook, NS, name)


def mesh_ready(cluster, name):
    nb = get_nb(cluster, name)
    return nb.status.tpu is not None and nb.status.tpu.mesh_ready


def condition(nb, ctype):
    return next((c for c in nb.status.conditions if c.type == ctype), None)


def repaired(cluster, name):
    nb = get_nb(cluster, name)
    if C.TPU_REPAIR_STATE_ANNOTATION in nb.metadata.annotations:
        return False
    c = condition(nb, C.TPU_DEGRADED_CONDITION)
    return c is not None and c.status == "False" and mesh_ready(cluster, name)


def has_event(cluster, reason):
    return any(e.reason == reason for e in cluster.client.list(Event, namespace=NS))


def pod_node(cluster, pod_name):
    return cluster.client.get(Pod, NS, pod_name).spec.node_name


def node_pool(cluster, node_name):
    return cluster.client.get(Node, "", node_name).metadata.labels[GKE_NODEPOOL_LABEL]


# ---------------------------------------------------------------------------
# host preemption: taint + maintenance notice -> checkpoint -> gang moves pool
# ---------------------------------------------------------------------------


def test_host_preemption_checkpoints_and_reschedules_to_ready(env):
    cluster, mgr, agents, repair = env
    # this test exercises the NODE-signal path (taint -> HostPreempted);
    # park the probe-absence dwell so a slow post-repair agent under full-
    # suite CPU load cannot open a spurious second HostUnreachable episode
    # whose slice.repair span would shadow the one under test
    repair.unreachable_dwell_s = 30.0
    interruptions0 = telemetry.slice_interruptions_total.value(cause="HostPreempted")
    repairs0 = telemetry.slice_repairs_total.value(result="repaired")

    cluster.client.create(mk_nb("trainer"))
    wait_for(lambda: mesh_ready(cluster, "trainer"), msg="first bring-up")

    # the workload wires its checkpoint hook (models/checkpoint.py
    # make_checkpoint_hook in a real pod; a recorder here)
    hook_calls = []
    for i in range(2):
        agents[f"trainer-{i}"].checkpoint_hook = (
            lambda: hook_calls.append(1) or {"step": 42}
        )

    victim_node = pod_node(cluster, "trainer-0")
    old_pool = node_pool(cluster, victim_node)
    # generous grace: the repair path must beat the platform drain
    cluster.preempt_node(victim_node, grace_s=10.0)

    # Degraded with the preemption cause, then repaired back to Ready
    wait_for(
        lambda: (c := condition(get_nb(cluster, "trainer"), C.TPU_DEGRADED_CONDITION))
        is not None and c.status == "True",
        msg="Degraded condition raised",
    )
    wait_for(lambda: repaired(cluster, "trainer"), msg="repaired to Ready")

    # checkpoint-before-evict contract: every host's hook was driven inside
    # the window and the acked step is recorded durably
    assert hook_calls, "checkpoint hooks never driven during the evict window"
    nb = get_nb(cluster, "trainer")
    assert nb.metadata.annotations.get(C.TPU_CHECKPOINT_SAVED_ANNOTATION) == "42"
    # repair state machine fully wound down
    for key in (
        C.TPU_REPAIR_STATE_ANNOTATION,
        C.TPU_REPAIR_STARTED_ANNOTATION,
        C.TPU_CHECKPOINT_REQUEST_ANNOTATION,
    ):
        assert key not in nb.metadata.annotations
    # (wait_for: a stale pod-condition mirror snapshot may transiently
    # resurrect the old Degraded value; the controller re-asserts it)
    wait_for(
        lambda: (c := condition(get_nb(cluster, "trainer"), C.TPU_DEGRADED_CONDITION))
        is not None and c.status == "False" and c.reason == "Repaired",
        msg="Degraded settled at False/Repaired",
    )

    # the gang fell back to the OTHER pool of the same topology (the original
    # pool cannot complete an all-or-nothing gang with a tainted host)
    pools = {node_pool(cluster, pod_node(cluster, f"trainer-{i}")) for i in range(2)}
    assert pools and old_pool not in pools, f"gang still in {old_pool}"
    assert len(pools) == 1, "gang split across ICI slices"

    # telemetry + trace closed the loop
    assert telemetry.slice_interruptions_total.value(cause="HostPreempted") \
        - interruptions0 >= 1
    assert telemetry.slice_repairs_total.value(result="repaired") - repairs0 >= 1
    spans = [
        s for s in tracing.recent_spans(name="slice.repair")
        if s["attributes"].get("notebook") == "trainer"
    ]
    assert spans, "no slice.repair span recorded"
    assert spans[-1]["attributes"]["cause"] == "HostPreempted"
    assert has_event(cluster, "SliceDegraded")
    assert has_event(cluster, "SliceRepaired")
    assert mgr.healthz()


def test_drain_without_repair_controller_still_detected_via_node_signal(env):
    """Even when the grace window lapses before the evict (tiny grace), the
    NodeLifecycle drain + node-level detection converge to Ready."""
    cluster, mgr, agents, repair = env
    cluster.client.create(mk_nb("rushed"))
    wait_for(lambda: mesh_ready(cluster, "rushed"), msg="bring-up")
    victim = pod_node(cluster, "rushed-0")
    cluster.preempt_node(victim, grace_s=0.05)  # drain beats the checkpoint
    wait_for(lambda: repaired(cluster, "rushed"), msg="repaired after drain")
    assert mgr.healthz()


# ---------------------------------------------------------------------------
# device faults: chip loss and ICI degradation through the probe agent
# ---------------------------------------------------------------------------


def test_chip_failure_flags_tpu_unhealthy_and_repairs(env):
    cluster, mgr, agents, repair = env
    interruptions0 = telemetry.slice_interruptions_total.value(cause="ChipFailure")
    cluster.client.create(mk_nb("chippy", accelerator="v5e", topology="2x2"))
    wait_for(lambda: mesh_ready(cluster, "chippy"), msg="bring-up")

    # the host's libtpu stops seeing half its chips
    agents["chippy-0"].monitor.chips = 2
    wait_for(
        lambda: (c := condition(get_nb(cluster, "chippy"), C.TPU_HEALTHY_CONDITION))
        is not None and c.status == "False" and c.reason == "ChipFailure",
        msg="TPUHealthy=False (ChipFailure)",
    )
    # replacement pod gets a fresh (healthy) agent incarnation -> repaired
    wait_for(lambda: repaired(cluster, "chippy"), msg="repaired")
    healthy = condition(get_nb(cluster, "chippy"), C.TPU_HEALTHY_CONDITION)
    assert healthy is not None and healthy.status == "True"
    assert telemetry.slice_interruptions_total.value(cause="ChipFailure") \
        - interruptions0 >= 1
    assert mgr.healthz()


def test_ici_degradation_flags_tpu_unhealthy_and_repairs(env):
    cluster, mgr, agents, repair = env
    cluster.client.create(mk_nb("icy", accelerator="v5e", topology="2x2"))
    wait_for(lambda: mesh_ready(cluster, "icy"), msg="bring-up")

    agents["icy-0"].monitor.ici_fault = True
    wait_for(
        lambda: (c := condition(get_nb(cluster, "icy"), C.TPU_HEALTHY_CONDITION))
        is not None and c.status == "False" and c.reason == "ICIDegraded",
        msg="TPUHealthy=False (ICIDegraded)",
    )
    wait_for(lambda: repaired(cluster, "icy"), msg="repaired")
    assert mgr.healthz()


# ---------------------------------------------------------------------------
# exhaustion: no capacity anywhere -> explicit terminal RepairFailed
# ---------------------------------------------------------------------------


def test_repair_exhaustion_emits_terminal_repair_failed(env):
    cluster, mgr, agents, repair = env
    failed0 = telemetry.slice_repairs_total.value(result="failed")
    cluster.client.create(mk_nb("doomed"))
    wait_for(lambda: mesh_ready(cluster, "doomed"), msg="bring-up")

    # take out EVERY v5p host: nowhere of the right topology remains
    v5p_nodes = [
        n.metadata.name
        for n in cluster.client.list(Node)
        if n.metadata.labels.get(GKE_NODEPOOL_LABEL, "").startswith("v5p")
    ]
    assert len(v5p_nodes) == 4
    for node in v5p_nodes:
        cluster.preempt_node(node, grace_s=0.1)

    wait_for(lambda: has_event(cluster, "RepairFailed"), msg="RepairFailed event")
    nb = get_nb(cluster, "doomed")
    assert nb.metadata.annotations.get(C.TPU_REPAIR_STATE_ANNOTATION) == "failed"
    # (wait_for: the controller re-asserts RepairFailed over any stale
    # mirror snapshot, level-triggered)
    wait_for(
        lambda: (c := condition(get_nb(cluster, "doomed"), C.TPU_DEGRADED_CONDITION))
        is not None and c.status == "True" and c.reason == "RepairFailed",
        msg="Degraded settled at RepairFailed",
    )
    assert telemetry.slice_repairs_total.value(result="failed") - failed0 >= 1

    # terminal is not a dead end: capacity comes back, the slice recovers,
    # and the failed episode is closed out
    for node in v5p_nodes:
        cluster.restore_node(node)
    wait_for(lambda: repaired(cluster, "doomed"), timeout=40,
             msg="recovered after capacity returned")
    assert mgr.healthz()


# ---------------------------------------------------------------------------
# goodput integrator: the downtime integral matches the episode's clock
# ---------------------------------------------------------------------------


def test_goodput_integrator_matches_episode_downtime(env):
    """Across a full repair episode (degraded -> checkpoint -> re-place ->
    ready) the goodput accounting must integrate downtime that matches the
    episode's measured MTTR — not zero (missed the episode) and not the
    whole lifetime (counting healthy time as downtime)."""
    cluster, mgr, agents, repair = env
    cluster.client.create(mk_nb("gp"))
    wait_for(lambda: mesh_ready(cluster, "gp"), msg="bring-up")
    # settle, then anchor the integrator so the healthy pre-fault interval
    # is part of the observed (uptime) side of the ledger
    time.sleep(0.5)
    # the accumulators live in the fleet accounting ledger since round 17:
    # totals() is (good_s, observed_s), downtime is their gap
    good0, observed0 = telemetry.goodput._ledger.totals()
    down0 = observed0 - good0

    victim = pod_node(cluster, "gp-0")
    cluster.preempt_node(victim, grace_s=5.0)
    wait_for(lambda: repaired(cluster, "gp"), msg="repaired")
    time.sleep(0.5)  # one more calm reconcile closes out the last interval

    span = next(
        s for s in reversed(tracing.recent_spans(name="slice.repair"))
        if s["attributes"].get("notebook") == "gp"
    )
    mttr = float(span["attributes"]["mttr_s"])
    good1, observed1 = telemetry.goodput._ledger.totals()
    downtime = (observed1 - good1) - down0
    observed = observed1 - observed0
    assert mttr > 0
    # the integral is sampled at reconcile boundaries: allow a probe-period
    # of slack either side, but it must track the episode's clock
    assert mttr * 0.5 - 0.5 <= downtime <= mttr * 1.5 + 1.0, (
        f"goodput integrated {downtime:.2f}s downtime for a {mttr:.2f}s episode"
    )
    assert observed > downtime, "healthy time must not count as downtime"
    assert 0.0 <= telemetry.slice_goodput_ratio.value() <= 1.0


# ---------------------------------------------------------------------------
# non-TPU notebooks are never touched
# ---------------------------------------------------------------------------


def test_cpu_notebook_untouched_by_repair(env):
    cluster, mgr, agents, repair = env
    cluster.add_cpu_pool("cpu", nodes=1)
    nb = Notebook()
    nb.metadata.name = "plain"
    nb.metadata.namespace = NS
    nb.spec.template.spec.containers = [Container(name="plain", image="jax:1")]
    cluster.client.create(nb)
    wait_for(
        lambda: get_nb(cluster, "plain").status.ready_replicas == 1,
        msg="cpu notebook ready",
    )
    time.sleep(0.5)
    annotations = get_nb(cluster, "plain").metadata.annotations
    assert C.TPU_REPAIR_STATE_ANNOTATION not in annotations
    assert condition(get_nb(cluster, "plain"), C.TPU_DEGRADED_CONDITION) is None


# ---------------------------------------------------------------------------
# the acceptance soak: seeded slice bad day, zero silently stuck notebooks
# ---------------------------------------------------------------------------


def _run_slice_soak(env, seed):
    cluster, mgr, agents, repair = env
    mttr_observed0 = telemetry.slice_repair_duration_seconds._totals.get((), 0)
    # fresh incident ledger (incl. the dedup memo — back-to-back soaks reuse
    # notebook names, and a deduped bundle would hide a real capture)
    recorder.clear()
    names = [("s-pod-0", "v5p", "2x2x2"), ("s-pod-1", "v5p", "2x2x2"),
             ("s-nb-0", "v5e", "2x2"), ("s-nb-1", "v5e", "2x2")]
    for name, acc, topo in names:
        cluster.client.create(mk_nb(name, accelerator=acc, topology=topo))
    for name, _, _ in names:
        wait_for(lambda n=name: mesh_ready(cluster, n), msg=f"{name} up")

    pod_nodes = {}
    for p in cluster.client.list(Pod, namespace=NS):
        if p.spec.node_name and p.metadata.labels.get(C.NOTEBOOK_NAME_LABEL):
            pod_nodes[p.metadata.name] = p.spec.node_name
    plan = seeded_slice_bad_day(
        cluster, seed=seed, pod_nodes=pod_nodes, agents=agents, grace_s=0.4
    )
    assert plan["preempted"], "the seeded schedule must preempt something"

    # maintenance ends: preempted hosts come back so repairs can land even
    # when no fallback pool of the right topology was free
    time.sleep(1.5)
    for node in plan["preempted"]:
        cluster.restore_node(node)

    # THE acceptance invariant: every notebook either returns to Ready (with
    # a slice.repair trace + MTTR observation) or carries an explicit
    # RepairFailed event — zero notebooks left silently stuck.
    def settled(name):
        nb = get_nb(cluster, name)
        state = nb.metadata.annotations.get(C.TPU_REPAIR_STATE_ANNOTATION, "")
        if state == "failed":
            return any(
                e.reason == "RepairFailed" and e.involved_object.name == name
                for e in cluster.client.list(Event, namespace=NS)
            )
        if state:
            return False  # mid-repair: not settled yet
        c = condition(nb, C.TPU_DEGRADED_CONDITION)
        return mesh_ready(cluster, name) and (c is None or c.status == "False")

    for name, _, _ in names:
        wait_for(lambda n=name: settled(n), timeout=60,
                 msg=f"{name} neither repaired nor explicitly RepairFailed")

    touched = set(plan["chip_loss"] + plan["ici"])
    touched |= {
        pod for pod, node in pod_nodes.items() if node in plan["preempted"]
    }
    assert touched, "seeded schedule touched nothing"
    # every faulted notebook that healed did so through a repair episode:
    # a slice.repair trace span + an MTTR observation exist for it
    healed_victims = [
        n for n, _, _ in names
        if any(pod.startswith(n + "-") for pod in touched)
        and repaired(cluster, n)
    ]
    span_names = {
        s["attributes"].get("notebook")
        for s in tracing.recent_spans(name="slice.repair")
    }
    for name in healed_victims:
        assert name in span_names, f"{name} repaired without a slice.repair trace"
    assert telemetry.slice_repair_duration_seconds._totals.get((), 0) \
        >= mttr_observed0 + len(healed_victims)
    # goodput stayed a sane ratio through the chaos
    goodput = telemetry.slice_goodput_ratio.value()
    assert 0.0 <= goodput <= 1.0
    # ISSUE 5: every Degraded entry snapshots the flight recorder — a bad
    # day that produced zero incident bundles is an observability failure
    # (ci/faults.sh reruns this soak as that gate)
    assert any(
        i["reason"] == "slice-degraded" for i in recorder.incidents()
    ), "no slice-degraded incident bundle captured during the bad day"
    assert mgr.healthz(), "a controller thread died during the slice bad day"


def test_seeded_slice_bad_day_no_silent_stuck(env):
    _run_slice_soak(env, seed=0x51CE)


@pytest.mark.slow
def test_slice_chaos_soak_second_seed(env):
    cluster, _, _, _ = env
    _run_slice_soak(env, seed=0xBAD51CE)
    cluster.faults.clear()
