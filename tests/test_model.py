"""Flagship transformer: correctness single-device, parity under full
fsdp/tp/sp sharding, and the driver entry contract."""
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding

from odh_kubeflow_tpu.models import (
    TransformerConfig,
    forward,
    init_params,
    loss_fn,
    make_train_step,
    param_specs,
)
from odh_kubeflow_tpu.parallel import MeshPlan, shard_batch


def tiny(dtype=jnp.float32, **kw):
    return TransformerConfig(
        vocab=64,
        d_model=64,
        n_layers=2,
        n_heads=4,
        d_ff=128,
        max_seq=64,
        dtype=dtype,
        use_flash=False,
        **kw,
    )


def data(batch=4, seq=32):
    return {
        "tokens": jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0, 64)
    }


def test_forward_shapes_and_f32_logits():
    cfg = tiny(dtype=jnp.bfloat16)
    params = init_params(jax.random.PRNGKey(0), cfg)
    logits = jax.jit(lambda p, t: forward(p, t, cfg))(params, data()["tokens"])
    assert logits.shape == (4, 32, 64)
    assert logits.dtype == jnp.float32  # loss math never in bf16


def test_loss_decreases():
    cfg = tiny()
    params = init_params(jax.random.PRNGKey(0), cfg)
    step, opt = make_train_step(cfg)
    opt_state = opt.init(params)
    batch = data()
    jstep = jax.jit(step)
    losses = []
    for _ in range(5):
        params, opt_state, loss = jstep(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


@pytest.mark.slow  # compile-heavy CPU-mesh parity (minutes): run via -m slow
def test_sharded_matches_single_device():
    """Same params/batch: loss on the fsdp=2,tp=2,sp=2 mesh (ring attention
    on) must match the unsharded loss — collectives change layout, not math."""
    cfg1 = tiny()
    params = init_params(jax.random.PRNGKey(0), cfg1)
    batch = data(batch=4, seq=32)
    base = float(jax.jit(lambda p, b: loss_fn(p, b, cfg1))(params, batch))

    mesh = MeshPlan(fsdp=2, tp=2, sp=2).build()
    cfg = tiny(seq_axis="sp")
    specs = param_specs(cfg, mesh)
    sharded = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs
    )
    sbatch = shard_batch(mesh, batch)
    got = float(
        jax.jit(lambda p, b: loss_fn(p, b, cfg, mesh=mesh))(sharded, sbatch)
    )
    assert got == pytest.approx(base, rel=1e-4)


def test_param_specs_match_param_tree():
    cfg = tiny()
    params = init_params(jax.random.PRNGKey(0), cfg)
    specs = param_specs(cfg)
    assert jax.tree_util.tree_structure(params) == jax.tree_util.tree_structure(
        specs
    )
    for p, s in zip(jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(specs)):
        assert len(s) <= p.ndim


@pytest.mark.slow  # compile-heavy CPU-mesh parity (minutes): run via -m slow
def test_graft_entry_contract():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.ndim == 3
    ge.dryrun_multichip(8)


# ---- grouped-query attention (GQA) ----


def test_gqa_params_and_forward_shapes():
    import jax
    import jax.numpy as jnp

    from odh_kubeflow_tpu.models import TransformerConfig, forward, init_params

    cfg = TransformerConfig(
        vocab=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=64,
        dtype=jnp.float32, use_flash=False, remat=False,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    # fused projection carries h + 2*kv head slots
    assert params["layers"]["wqkv"].shape == (2, 32, 4 + 2 * 2, 8)
    tokens = jnp.ones((2, 16), jnp.int32)
    logits = forward(params, tokens, cfg)
    assert logits.shape == (2, 16, 64)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_gqa_equals_mha_when_kv_heads_match():
    """n_kv_heads == n_heads must be numerically identical to the default."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from odh_kubeflow_tpu.models import TransformerConfig, forward, init_params

    base = dict(vocab=64, d_model=32, n_layers=1, n_heads=4, d_ff=64,
                dtype=jnp.float32, use_flash=False, remat=False)
    cfg_mha = TransformerConfig(**base)
    cfg_gqa = TransformerConfig(**base, n_kv_heads=4)
    params = init_params(jax.random.PRNGKey(0), cfg_mha)
    tokens = jnp.ones((1, 8), jnp.int32)
    a = forward(params, tokens, cfg_mha)
    b = forward(params, tokens, cfg_gqa)
    assert np.allclose(np.asarray(a), np.asarray(b))


def test_gqa_decode_matches_forward_and_shrinks_cache():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from odh_kubeflow_tpu.models import (
        TransformerConfig,
        decode_step,
        forward,
        init_params,
        prefill,
    )

    cfg = TransformerConfig(
        vocab=97, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=64,
        dtype=jnp.float32, use_flash=False, remat=False,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, cfg.vocab)
    logits, cache = prefill(params, prompt, cfg, max_seq=12)
    # the cache stores kv_heads, not n_heads — the GQA memory win
    assert cache.k.shape == (2, 2, 12, 2, 8)
    full = forward(params, prompt, cfg)
    assert np.allclose(np.asarray(logits), np.asarray(full[:, -1]), atol=1e-3)
    seq = prompt
    for _ in range(3):
        nxt = jnp.argmax(logits, axis=-1)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        logits, cache = decode_step(params, cache, nxt, cfg)
        full = forward(params, seq, cfg)
        assert np.allclose(np.asarray(logits), np.asarray(full[:, -1]), atol=1e-3)


def test_gqa_sharded_train_step():
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from odh_kubeflow_tpu.models import (
        TransformerConfig,
        init_params,
        make_train_step,
        param_specs,
    )
    from odh_kubeflow_tpu.parallel import MeshPlan, shard_batch

    mesh = MeshPlan.auto(8, want_tp=2, want_sp=2).build(jax.devices()[:8])
    cfg = TransformerConfig(
        vocab=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=64,
        dtype=jnp.float32, use_flash=False, remat=False,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    specs = param_specs(cfg, mesh)
    params = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs
    )
    step, opt = make_train_step(cfg, mesh=mesh)
    opt_state = opt.init(params)
    batch = shard_batch(mesh, {"tokens": jnp.ones((4, 16), jnp.int32)})
    _, _, loss = jax.jit(step)(params, opt_state, batch)
    assert jnp.isfinite(loss)


def test_gqa_indivisible_fused_axis_replicates():
    """GQA fused head axis (n_heads + 2*kv) not divisible by tp must fall
    back to replication, not crash at device_put."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from odh_kubeflow_tpu.models import TransformerConfig, init_params, param_specs
    from odh_kubeflow_tpu.parallel import MeshPlan

    mesh = MeshPlan.auto(8, want_tp=8).build(jax.devices()[:8])
    # fused axis = 8 + 2*2 = 12, not divisible by tp=8
    cfg = TransformerConfig(
        vocab=64, d_model=64, n_layers=1, n_heads=8, n_kv_heads=2, d_ff=64,
        dtype=jnp.float32, use_flash=False, remat=False,
    )
    specs = param_specs(cfg, mesh)
    assert specs["layers"]["wqkv"][2] is None  # replicated fallback
    params = init_params(jax.random.PRNGKey(0), cfg)
    jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs
    )


def test_remat_policies_preserve_loss_and_grads():
    """remat_policy changes WHAT the layer checkpoint saves, never the math:
    loss and gradients identical across "", "dots", "attn" (and remat off)."""
    from dataclasses import replace

    import numpy as np

    base = TransformerConfig(
        vocab=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        dtype=jnp.float32, use_flash=False, remat=True,
    )
    params = init_params(jax.random.PRNGKey(0), base)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, base.vocab)

    def lg(cfg):
        return jax.value_and_grad(loss_fn)(params, {"tokens": tokens}, cfg)

    ref_loss, ref_g = lg(replace(base, remat=False))
    for policy in ("", "dots", "attn", "flash"):
        loss, g = lg(replace(base, remat_policy=policy))
        assert np.allclose(float(loss), float(ref_loss), atol=1e-6), policy
        for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_flatten_with_path(g)[0],
            jax.tree_util.tree_flatten_with_path(ref_g)[0],
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-6, rtol=1e-5,
                err_msg=f"{policy} {jax.tree_util.keystr(pa)}",
            )
    import pytest

    with pytest.raises(ValueError):
        loss_fn(params, {"tokens": tokens}, replace(base, remat_policy="bogus"))


def test_flash_remat_policy_with_live_kernel_residuals():
    """remat_policy="flash"/"attn" with the pallas kernel actually running
    (interpret mode): the checkpoint_name'd (out, lse) residuals exist in
    the traced region, the policy pins them, and loss/gradients stay
    identical to remat=False. This is the path the no-op "attn" bug hid in
    (the policy saved the post-projection output but the kernel vjp still
    reran the forward for lse); the fix is only exercised when the kernel
    path is live — the use_flash=False test above degrades to
    save-nothing by design."""
    from dataclasses import replace
    from functools import partial

    import numpy as np

    import odh_kubeflow_tpu.models.transformer as T

    # block_k >= 128 is the kernel's floor, so seq must be >= 128
    base = TransformerConfig(
        vocab=64, d_model=128, n_layers=2, n_heads=2, d_ff=128, max_seq=128,
        dtype=jnp.float32, use_flash=True, remat=True,
    )
    params = init_params(jax.random.PRNGKey(0), base)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 128), 0, base.vocab)

    orig = T.flash_attention
    T.flash_attention = partial(orig, interpret=True)
    try:
        def lg(cfg):
            return jax.value_and_grad(loss_fn)(params, {"tokens": tokens}, cfg)

        ref_loss, ref_g = lg(replace(base, remat=False))
        for policy in ("", "flash", "attn"):
            loss, g = lg(replace(base, remat_policy=policy))
            assert np.allclose(float(loss), float(ref_loss), atol=1e-6), policy
            for (pa, a), (pb, b) in zip(
                jax.tree_util.tree_flatten_with_path(g)[0],
                jax.tree_util.tree_flatten_with_path(ref_g)[0],
            ):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4,
                    err_msg=f"{policy} {jax.tree_util.keystr(pa)}",
                )
    finally:
        T.flash_attention = orig


def test_causal_ce_matches_log_softmax_reference():
    """causal_ce/next_token_ce (lse-form over full-shape logits, roll+mask)
    equal the textbook sliced log_softmax formulation exactly — the CE
    rewrite is a memory-traffic optimization, never a math change. Also
    pins the explicit-targets-without-mask path (a latent TypeError before
    round 5's causal_ce: mask=None fell through to `ll * None`)."""
    import numpy as np

    from odh_kubeflow_tpu.models.transformer import causal_ce, next_token_ce

    b, s, V = 2, 16, 32
    logits = jax.random.normal(jax.random.PRNGKey(0), (b, s, V), jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (b, s), 0, V)

    # textbook reference: slice, log_softmax, gather, mean
    sliced, targets = logits[:, :-1], tokens[:, 1:]
    logp = jax.nn.log_softmax(sliced, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    ref = -jnp.mean(ll)

    got = next_token_ce(logits, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-6)

    # explicit targets WITHOUT a mask: every position counts, plain mean
    tg = jax.random.randint(jax.random.PRNGKey(2), (b, s), 0, V)
    logp_all = jax.nn.log_softmax(logits, axis=-1)
    ll_all = jnp.take_along_axis(logp_all, tg[..., None], axis=-1)[..., 0]
    np.testing.assert_allclose(
        np.asarray(causal_ce(logits, tg)), np.asarray(-jnp.mean(ll_all)),
        rtol=1e-6,
    )

    # explicit targets WITH a mask: masked-mean semantics
    mask = (jnp.arange(s)[None, :] % 2 == 0).astype(jnp.float32)
    mask = jnp.broadcast_to(mask, (b, s))
    want = -jnp.sum(ll_all * mask) / jnp.sum(mask)
    np.testing.assert_allclose(
        np.asarray(causal_ce(logits, tg, mask)), np.asarray(want), rtol=1e-6
    )


@pytest.mark.slow  # compile-heavy CPU-mesh parity (minutes): run via -m slow
def test_zigzag_seq_layout_loss_matches_natural():
    """cfg.seq_layout="zigzag" + make_zigzag_batch on an sp=2 mesh: the LM
    loss equals the natural-order loss on the full batch (the mean over
    tokens is permutation-invariant and targets were shifted in natural
    order), with GQA ring attention running load-balanced."""
    from dataclasses import replace

    import numpy as np
    from jax.sharding import NamedSharding

    from odh_kubeflow_tpu.models import param_specs
    from odh_kubeflow_tpu.models.transformer import make_zigzag_batch
    from odh_kubeflow_tpu.parallel import MeshPlan, shard_batch

    cfg = TransformerConfig(
        vocab=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=64,
        dtype=jnp.float32, use_flash=False, remat=False, seq_axis="sp",
        seq_layout="zigzag",
    )
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, cfg.vocab)
    params = init_params(jax.random.PRNGKey(0), cfg)

    # natural-order reference: the STANDARD contiguous loss (logits[:, :-1]
    # vs tokens[:, 1:]) — make_zigzag_batch's loss_mask makes the zigzag
    # loss equal it exactly (the wrap-around label is masked out)
    nat_cfg = replace(cfg, seq_axis="", seq_layout="contiguous")
    ref = loss_fn(params, {"tokens": tokens}, nat_cfg)

    mesh = MeshPlan(sp=2).build(jax.devices()[:2])
    specs = param_specs(cfg, mesh)
    sharded = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs
    )
    zz = shard_batch(mesh, make_zigzag_batch(tokens, sp=2))
    got = jax.jit(lambda p, b: loss_fn(p, b, cfg, mesh))(sharded, zz)
    assert np.allclose(float(got), float(ref), atol=1e-5)
