"""Flagship transformer: correctness single-device, parity under full
fsdp/tp/sp sharding, and the driver entry contract."""
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding

from odh_kubeflow_tpu.models import (
    TransformerConfig,
    forward,
    init_params,
    loss_fn,
    make_train_step,
    param_specs,
)
from odh_kubeflow_tpu.parallel import MeshPlan, shard_batch


def tiny(dtype=jnp.float32, **kw):
    return TransformerConfig(
        vocab=64,
        d_model=64,
        n_layers=2,
        n_heads=4,
        d_ff=128,
        max_seq=64,
        dtype=dtype,
        use_flash=False,
        **kw,
    )


def data(batch=4, seq=32):
    return {
        "tokens": jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0, 64)
    }


def test_forward_shapes_and_f32_logits():
    cfg = tiny(dtype=jnp.bfloat16)
    params = init_params(jax.random.PRNGKey(0), cfg)
    logits = jax.jit(lambda p, t: forward(p, t, cfg))(params, data()["tokens"])
    assert logits.shape == (4, 32, 64)
    assert logits.dtype == jnp.float32  # loss math never in bf16


def test_loss_decreases():
    cfg = tiny()
    params = init_params(jax.random.PRNGKey(0), cfg)
    step, opt = make_train_step(cfg)
    opt_state = opt.init(params)
    batch = data()
    jstep = jax.jit(step)
    losses = []
    for _ in range(5):
        params, opt_state, loss = jstep(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_sharded_matches_single_device():
    """Same params/batch: loss on the fsdp=2,tp=2,sp=2 mesh (ring attention
    on) must match the unsharded loss — collectives change layout, not math."""
    cfg1 = tiny()
    params = init_params(jax.random.PRNGKey(0), cfg1)
    batch = data(batch=4, seq=32)
    base = float(jax.jit(lambda p, b: loss_fn(p, b, cfg1))(params, batch))

    mesh = MeshPlan(fsdp=2, tp=2, sp=2).build()
    cfg = tiny(seq_axis="sp")
    specs = param_specs(cfg, mesh)
    sharded = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs
    )
    sbatch = shard_batch(mesh, batch)
    got = float(
        jax.jit(lambda p, b: loss_fn(p, b, cfg, mesh=mesh))(sharded, sbatch)
    )
    assert got == pytest.approx(base, rel=1e-4)


def test_param_specs_match_param_tree():
    cfg = tiny()
    params = init_params(jax.random.PRNGKey(0), cfg)
    specs = param_specs(cfg)
    assert jax.tree_util.tree_structure(params) == jax.tree_util.tree_structure(
        specs
    )
    for p, s in zip(jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(specs)):
        assert len(s) <= p.ndim


def test_graft_entry_contract():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.ndim == 3
    ge.dryrun_multichip(8)
