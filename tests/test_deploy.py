"""Deploy layer: CRD generation, overlays, params, drift (SURVEY §2.3)."""
import os
import subprocess
import sys

import pytest
import yaml

from odh_kubeflow_tpu.deploy import (
    OVERLAYS,
    build,
    load_params,
    merge_patch,
    notebook_crd,
    render_yaml,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _by_kind(manifests, kind):
    return [m for m in manifests if m["kind"] == kind]


def test_crd_serves_all_versions_with_hub_storage():
    crd = notebook_crd()
    versions = {v["name"]: v for v in crd["spec"]["versions"]}
    assert set(versions) == {"v1beta1", "v1", "v1alpha1"}
    assert versions["v1beta1"]["storage"] is True
    assert not versions["v1"]["storage"] and not versions["v1alpha1"]["storage"]


def test_crd_schema_has_tpu_block_and_podspec():
    crd = notebook_crd()
    schema = crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"]
    spec = schema["properties"]["spec"]["properties"]
    tpu = spec["tpu"]["properties"]
    assert tpu["accelerator"] == {"type": "string"}
    assert tpu["chips"] == {"type": "integer"}
    pod = spec["template"]["properties"]["spec"]
    assert "containers" in pod["properties"]
    assert pod["x-kubernetes-preserve-unknown-fields"] is True
    status = schema["properties"]["status"]["properties"]
    assert status["tpu"]["properties"]["chipsVisible"] == {"type": "integer"}


def test_base_build_is_complete_and_yaml_round_trips():
    manifests = build("base")
    kinds = sorted(m["kind"] for m in manifests)
    for expected in [
        "CustomResourceDefinition",
        "ClusterRole",
        "ClusterRoleBinding",
        "ConfigMap",
        "Deployment",
        "MutatingWebhookConfiguration",
        "Namespace",
        "Service",
        "ServiceAccount",
    ]:
        assert expected in kinds, f"missing {expected}"
    docs = list(yaml.safe_load_all(render_yaml(manifests)))
    assert docs == manifests


def test_params_pin_images():
    params = {"odh-notebook-controller-image": "example.com/ctrl:v9",
              "namespace": "custom-ns"}
    manifests = build("base", params)
    dep = _by_kind(manifests, "Deployment")[0]
    assert dep["metadata"]["namespace"] == "custom-ns"
    c = dep["spec"]["template"]["spec"]["containers"][0]
    assert c["image"] == "example.com/ctrl:v9"
    env = {e["name"]: e["value"] for e in c["env"]}
    assert env["K8S_NAMESPACE"] == "custom-ns"


def test_webhook_fail_policy_and_service_wiring():
    manifests = build("base")
    wh = _by_kind(manifests, "MutatingWebhookConfiguration")[0]["webhooks"][0]
    assert wh["failurePolicy"] == "Fail"
    svc_ref = wh["clientConfig"]["service"]
    names = {m["metadata"]["name"] for m in _by_kind(manifests, "Service")}
    assert svc_ref["name"] in names
    assert {"v1beta1", "v1", "v1alpha1"} == set(wh["rules"][0]["apiVersions"])


def test_standalone_overlay_enables_culling_with_ci_cadence():
    cm = _by_kind(build("standalone"), "ConfigMap")[0]
    assert cm["data"]["ENABLE_CULLING"] == "true"
    assert cm["data"]["CULL_IDLE_TIME"] == "60"
    assert cm["data"]["IDLENESS_CHECK_PERIOD"] == "5"


def test_gke_overlay_adds_gateway_and_certmanager():
    manifests = build("gke")
    gws = _by_kind(manifests, "Gateway")
    assert gws and gws[0]["spec"]["gatewayClassName"].startswith("gke-l7")
    wh = _by_kind(manifests, "MutatingWebhookConfiguration")[0]
    assert "cert-manager.io/inject-ca-from" in wh["metadata"]["annotations"]


def test_load_params_parses_and_rejects_garbage():
    p = load_params("# comment\nfoo=bar\n\nbaz = qux \n")
    assert p == {"foo": "bar", "baz": "qux"}
    with pytest.raises(ValueError):
        load_params("not-a-param")


def test_merge_patch_rfc7386_semantics():
    assert merge_patch({"a": {"b": 1, "c": 2}}, {"a": {"b": None, "d": 3}}) == {
        "a": {"c": 2, "d": 3}
    }
    assert merge_patch({"a": [1, 2]}, {"a": [3]}) == {"a": [3]}


def test_unmatched_overlay_patch_fails_build():
    from odh_kubeflow_tpu.deploy.overlay import apply_patches

    with pytest.raises(ValueError, match="matched no manifest"):
        apply_patches([], [{"kind": "ConfigMap", "metadata": {"name": "x"}}])


def test_committed_deploy_tree_is_not_drifted(tmp_path):
    """ci/generate_manifests.sh analog: regenerating must match deploy/."""
    from odh_kubeflow_tpu.deploy.__main__ import generate_tree

    committed = os.path.join(REPO, "deploy")
    if not os.path.exists(os.path.join(committed, "base", "manifests.yaml")):
        pytest.skip("deploy tree not generated yet")
    generate_tree(str(tmp_path), os.path.join(committed, "params.env"))
    for rel in ["base/manifests.yaml"] + [
        f"overlays/{n}/manifests.yaml" for n in sorted(OVERLAYS) if n != "base"
    ]:
        with open(os.path.join(committed, rel)) as f:
            want = f.read()
        with open(os.path.join(tmp_path, rel)) as f:
            got = f.read()
        assert got == want, f"deploy/{rel} drifted — run python -m odh_kubeflow_tpu.deploy generate"


@pytest.mark.deploylint
def test_build_manifests_check_mode_catches_unregenerated_edit(tmp_path):
    """ci/build_manifests.sh --check (ISSUE 14): clean on the committed
    tree, and a hand-edit to the committed YAML without regenerating fails
    the gate — non-mutating, so the working tree is untouched either way."""
    import shutil

    subprocess.run(
        ["bash", os.path.join("ci", "build_manifests.sh"), "--check"],
        cwd=REPO,
        check=True,
        capture_output=True,
        text=True,
    )

    # sandbox repo: the real script + package against a doctored deploy/
    sandbox = tmp_path / "repo"
    (sandbox / "ci").mkdir(parents=True)
    shutil.copy(
        os.path.join(REPO, "ci", "build_manifests.sh"), sandbox / "ci"
    )
    shutil.copytree(os.path.join(REPO, "deploy"), sandbox / "deploy")
    base = sandbox / "deploy" / "base" / "manifests.yaml"
    base.write_text(base.read_text().replace("replicas: 1", "replicas: 3", 1))
    env = dict(os.environ, PYTHONPATH=REPO)
    out = subprocess.run(
        ["bash", "ci/build_manifests.sh", "--check"],
        cwd=sandbox,
        env=env,
        capture_output=True,
        text=True,
    )
    assert out.returncode == 1, out.stdout + out.stderr
    assert "drifted" in out.stderr
    # ...and the doctored tree was not silently rewritten by the check
    assert "replicas: 3" in base.read_text()


def test_cli_build_prints_yaml():
    out = subprocess.run(
        [sys.executable, "-m", "odh_kubeflow_tpu.deploy", "build", "standalone"],
        capture_output=True,
        text=True,
        cwd=REPO,
        check=True,
    ).stdout
    docs = list(yaml.safe_load_all(out))
    assert any(d["kind"] == "CustomResourceDefinition" for d in docs)
