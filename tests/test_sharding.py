"""Sharded multi-manager control plane (ISSUE 13): deterministic hash
partition of the reconcile keyspace, per-shard leases with standby takeover
within lease bounds, and write fencing — including the VERDICT r5 weak-#7
scenarios: stand-down before the next write on lease loss, dead-elector
detection, and a fenced ex-leader's retrying in-flight write rejected (not
duplicated).

The kill-the-leader-mid-storm test is part of the ISSUE 13 tentpole: an
object storm runs while the active shard leader dies; the standby must take
over inside the lease window and every owned object must still converge with
zero fenced-off duplicate writes.
"""
import threading
import time

import pytest

from odh_kubeflow_tpu.api.coordination import Lease
from odh_kubeflow_tpu.api.core import ConfigMap
from odh_kubeflow_tpu.apimachinery import ForbiddenError, NotFoundError, TooManyRequestsError
from odh_kubeflow_tpu.cluster import Client, Store
from odh_kubeflow_tpu.cluster.flowcontrol import FlowController, FlowSchema, PriorityLevel
from odh_kubeflow_tpu.runtime import Manager, Request
from odh_kubeflow_tpu.runtime import metrics as rm
from odh_kubeflow_tpu.runtime.manager import LeaderElector, ShardSpec

pytestmark = pytest.mark.flowcontrol

NS = "sharded"


def mk_cm(name, ns=NS):
    cm = ConfigMap()
    cm.metadata.name = name
    cm.metadata.namespace = ns
    return cm


def wait_for(fn, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(0.02)
    raise AssertionError(f"timeout: {msg}")


# ---------------------------------------------------------------------------
# the partition itself
# ---------------------------------------------------------------------------


def test_shardspec_partitions_exactly_once():
    shards = [ShardSpec(i, 3) for i in range(3)]
    counts = [0, 0, 0]
    for i in range(300):
        owners = [s.owns(NS, f"obj-{i}") for s in shards]
        assert sum(owners) == 1, f"obj-{i} owned by {sum(owners)} shards"
        counts[owners.index(True)] += 1
    # crc32 spreads a mixed population roughly evenly — no shard starves
    assert all(c > 50 for c in counts), counts


def test_shardspec_single_shard_owns_all_and_validates():
    assert ShardSpec(0, 1).owns("any", "thing")
    with pytest.raises(ValueError):
        ShardSpec(2, 2)
    with pytest.raises(ValueError):
        ShardSpec(-1, 3)
    with pytest.raises(ValueError):
        ShardSpec(0, 0)


def test_builder_drops_non_owned_keys():
    """Two managers, shards 0/2 and 1/2, over one store: every object is
    reconciled by exactly its owning shard."""
    store = Store()
    client = Client(store)
    seen = {0: set(), 1: set()}
    mgrs = []
    for idx in (0, 1):
        mgr = Manager(store, shard=ShardSpec(idx, 2))

        def reconcile(req: Request, idx=idx):
            seen[idx].add(req.name)
            return None

        mgr.builder(f"shard-{idx}").for_(ConfigMap).complete(reconcile)
        mgr.start()
        mgrs.append(mgr)
    try:
        names = [f"cm-{i}" for i in range(24)]
        for n in names:
            client.create(mk_cm(n))
        for mgr in mgrs:
            assert mgr.wait_idle()
        assert seen[0] | seen[1] == set(names)
        assert not (seen[0] & seen[1]), "an object reconciled by both shards"
        for n in names:
            owner = 0 if ShardSpec(0, 2).owns(NS, n) else 1
            assert n in seen[owner]
    finally:
        for mgr in mgrs:
            mgr.stop()


def test_per_shard_lease_names_are_independent():
    store = Store()
    m0 = Manager(store, leader_election=True, leader_election_id="op",
                 shard=ShardSpec(0, 2), lease_duration=1.0, renew_period=0.2)
    m1 = Manager(store, leader_election=True, leader_election_id="op",
                 shard=ShardSpec(1, 2), lease_duration=1.0, renew_period=0.2)
    try:
        assert m0.elector.lease_name == "op-shard-0"
        assert m1.elector.lease_name == "op-shard-1"
        # both become leader simultaneously: the leases don't contend
        m0.start(wait_for_leadership_timeout=5)
        m1.start(wait_for_leadership_timeout=5)
        assert m0.elector.is_leader.is_set() and m1.elector.is_leader.is_set()
    finally:
        m0.stop()
        m1.stop()


# ---------------------------------------------------------------------------
# tentpole: kill the active shard leader mid-storm
# ---------------------------------------------------------------------------


def test_kill_shard_leader_mid_storm_standby_takes_over():
    LEASE, RENEW = 1.2, 0.3
    store = Store()
    store.flowcontrol = FlowController()  # the storm runs through admission
    driver = Client(store)
    shard = ShardSpec(0, 2)
    fenced0 = rm.fenced_writes_total.value()

    def build(tag):
        mgr = Manager(store, leader_election=True, leader_election_id="storm",
                      shard=shard, lease_duration=LEASE, renew_period=RENEW)
        seen = set()

        def reconcile(req: Request):
            seen.add(req.name)
            # a real write per object, so fencing has something to fence:
            # stamp the owning manager (guarded: steady state stops writing)
            try:
                cm = mgr.client.get(ConfigMap, req.namespace, req.name)
            except NotFoundError:
                return None
            if cm.metadata.annotations.get("owned-by") != tag:
                mgr.client.patch(
                    ConfigMap, req.namespace, req.name,
                    {"metadata": {"annotations": {"owned-by": tag}}},
                )
            return None

        mgr.builder("stamper").for_(ConfigMap).complete(reconcile)
        return mgr, seen

    mgr_a, seen_a = build("a")
    mgr_b, seen_b = build("b")
    mgr_a.start(wait_for_leadership_timeout=5)
    b_started = threading.Event()

    def start_standby():
        mgr_b.start(wait_for_leadership_timeout=30)
        b_started.set()

    standby = threading.Thread(target=start_standby, daemon=True)
    standby.start()
    time.sleep(2 * RENEW)
    assert not b_started.is_set(), "standby grabbed a held lease"

    names = [f"storm-{w}-{i}" for w in range(4) for i in range(10)]
    stop_at = len(names) // 2  # kill the leader halfway through the storm

    def create_range(lo, hi):
        for n in names[lo:hi]:
            for _ in range(20):  # drive writes ride out transient sheds
                try:
                    driver.create(mk_cm(n))
                    break
                except TooManyRequestsError:
                    time.sleep(0.05)

    create_range(0, stop_at)
    t_kill = time.monotonic()
    mgr_a.stop()  # the active shard leader dies mid-storm
    create_range(stop_at, len(names))  # the storm keeps coming

    assert b_started.wait(LEASE + 4 * RENEW + 2.0), "standby never took over"
    takeover = time.monotonic() - t_kill
    # within lease bounds: the old lease must first age out (>= LEASE since
    # the last renew), then one standby acquire tick lands
    assert takeover <= LEASE + 2 * RENEW + 1.5, f"takeover took {takeover:.2f}s"
    try:
        assert mgr_b.wait_idle()
        owned = [n for n in names if shard.owns(NS, n)]
        not_owned = [n for n in names if not shard.owns(NS, n)]
        assert owned and not_owned  # the storm actually spans the partition
        wait_for(
            lambda: all(
                driver.get(ConfigMap, NS, n).metadata.annotations.get("owned-by") == "b"
                for n in owned
            ),
            msg="new leader re-stamped every owned object",
        )
        for n in not_owned:  # the shard filter held through failover
            assert "owned-by" not in driver.get(ConfigMap, NS, n).metadata.annotations
        assert seen_b.issuperset(owned)
        # zero fenced-off duplicate writes: the dying leader drained cleanly
        # inside its lease, so nothing ever hit the fence
        assert rm.fenced_writes_total.value() - fenced0 == 0
        # and failover traffic rode the exempt level untouched by the storm
        s = store.flowcontrol.summary()
        assert s["exempt"]["dispatched"] > 0 and s["exempt"]["rejected"] == 0
    finally:
        mgr_b.stop()


# ---------------------------------------------------------------------------
# VERDICT r5 weak #7: the three fencing regression scenarios
# ---------------------------------------------------------------------------


def test_lost_lease_stands_manager_down_and_fences_writes():
    """(a) leadership lost mid-flight: the manager stands down before the
    next write, and that write is refused by the fence."""
    store = Store()
    mgr = Manager(store, leader_election=True, leader_election_id="loss",
                  lease_duration=1.0, renew_period=0.15)
    mgr.builder("noop").for_(ConfigMap).complete(lambda req: None)
    mgr.start(wait_for_leadership_timeout=5)
    fenced0 = rm.fenced_writes_total.value()
    try:
        # a rival steals the lease with a fresh renew_time (the partition-
        # heals-on-the-wrong-side shape); the elector's next tick sees a
        # healthy foreign holder and must stand down
        rival = Client(store)
        lease = rival.get(Lease, "kube-system", "loss")
        lease.spec.holder_identity = "rival"
        lease.spec.renew_time = LeaderElector._now()
        rival.update(lease)
        wait_for(lambda: not mgr.elector.is_leader.is_set(), timeout=5,
                 msg="leadership relinquished")
        wait_for(lambda: not mgr._started, timeout=5,
                 msg="on_stopped_leading stood the manager down")
        with pytest.raises(ForbiddenError):
            mgr.client.create(mk_cm("post-loss"))
        assert rm.fenced_writes_total.value() - fenced0 == 1
        with pytest.raises(NotFoundError):
            rival.get(ConfigMap, NS, "post-loss")
    finally:
        mgr.stop()


def test_dead_elector_with_leader_flag_set_fails_healthz():
    """(b) elector thread dies while is_leader is still set — the silent
    split-brain precursor. healthz() must go false so the liveness probe
    restarts the process."""
    store = Store()
    mgr = Manager(store, leader_election=True, leader_election_id="dead",
                  lease_duration=1.0, renew_period=0.1)
    mgr.start(wait_for_leadership_timeout=5)
    try:
        assert mgr.healthz()
        mgr.elector.stop()  # thread exits WITHOUT clearing is_leader
        wait_for(lambda: not mgr.elector._thread.is_alive(), timeout=5,
                 msg="elector thread exited")
        assert mgr.elector.is_leader.is_set()  # the dangerous state
        assert mgr.healthz() is False
    finally:
        mgr.stop()


def test_fence_flips_between_throttle_retries_write_rejected_not_duplicated():
    """(c) a write sheds 429, and the lease lapses during the Retry-After
    sleep: the per-attempt fence check must reject the retry — the object is
    never written by the ex-leader."""
    store = Store()
    store.flowcontrol = FlowController(
        schemas=[FlowSchema("catch-all", "default")],
        levels=[PriorityLevel("default", seats=1, queue_length=0,
                              queue_timeout_s=0.05)],
    )
    client = Client(store)
    # fence callable: open at entry (attempt 0 proceeds and sheds), closed
    # by the time the retry re-checks — deterministic lease-lapse-mid-retry
    states = [True]
    client.write_fence = lambda: bool(states) and states.pop(0)
    fenced0 = rm.fenced_writes_total.value()
    hog = store.flowcontrol.admit("hog")
    try:
        with pytest.raises(ForbiddenError):
            client.create(mk_cm("in-flight"))
    finally:
        hog.release()
    assert rm.fenced_writes_total.value() - fenced0 == 1
    with pytest.raises(NotFoundError):
        Client(store).get(ConfigMap, NS, "in-flight")
