"""Culling: annotation state machine, Jupyter+TPU dual idleness signal,
cull -> slice freed, reactivation. Probes travel real HTTP sockets to the
in-pod agent served by the kubelet sim."""
import time

import pytest

from odh_kubeflow_tpu.api.core import Container, Pod
from odh_kubeflow_tpu.api.notebook import Notebook, TPUSpec
from odh_kubeflow_tpu.cluster import PodDecision, SimCluster
from odh_kubeflow_tpu.controllers import (
    Config,
    CullingReconciler,
    NotebookReconciler,
    constants as C,
)
from odh_kubeflow_tpu.probe import sim_agent_behavior
from odh_kubeflow_tpu.runtime import Manager

FAST = Config(
    enable_culling=True,
    cull_idle_time_min=1.5 / 60.0,  # 1.5 s idle threshold
    idleness_check_period_min=0.1 / 60.0,  # 0.1 s cadence
)


@pytest.fixture()
def env():
    cluster = SimCluster().start()
    cluster.add_tpu_pool("pool", "v5e", "2x2")
    cluster.add_cpu_pool("cpu", nodes=1)
    mgr = Manager(cluster.store)
    NotebookReconciler(mgr, FAST).setup()
    CullingReconciler(mgr, FAST, http_get=cluster.http_get).setup()

    # every notebook pod runs a real agent; tests script its state
    agents = {}
    cluster.add_pod_behavior(
        sim_agent_behavior(agents, duty=0.0, kernels_busy=False, chips=4)
    )
    mgr.start()
    yield cluster, mgr, agents
    mgr.stop()
    cluster.stop()


def mk_nb(name, tpu=None):
    nb = Notebook()
    nb.metadata.name = name
    nb.metadata.namespace = "user"
    nb.spec.template.spec.containers = [Container(name=name, image="jax:1")]
    if tpu:
        nb.spec.tpu = tpu
    return nb


def wait_for(fn, timeout=10, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(0.05)
    raise AssertionError(f"timeout: {msg}")


def get_nb(cluster, name):
    return cluster.client.get(Notebook, "user", name)


def test_idle_notebook_is_culled_and_annotations_tracked(env):
    cluster, mgr, agents = env
    cluster.client.create(mk_nb("sleepy"))
    # annotations initialize
    wait_for(
        lambda: C.LAST_ACTIVITY_ANNOTATION in get_nb(cluster, "sleepy").metadata.annotations
        or C.STOP_ANNOTATION in get_nb(cluster, "sleepy").metadata.annotations,
        msg="activity annotation initialized",
    )
    # idle kernels + no TPU -> culled after the 0.5s threshold
    wait_for(
        lambda: C.STOP_ANNOTATION in get_nb(cluster, "sleepy").metadata.annotations,
        msg="culled",
    )
    # slice freed
    wait_for(
        lambda: not cluster.client.list(
            Pod, namespace="user", labels={C.NOTEBOOK_NAME_LABEL: "sleepy"}
        ),
        msg="pods gone",
    )
    # culling removed the activity annotations once stopped
    wait_for(
        lambda: C.LAST_ACTIVITY_ANNOTATION
        not in get_nb(cluster, "sleepy").metadata.annotations,
        msg="activity annotations removed",
    )


def test_busy_kernel_prevents_culling(env):
    cluster, mgr, agents = env
    cluster.client.create(mk_nb("worker"))
    wait_for(lambda: "worker-0" in agents, msg="pod up")
    agents["worker-0"].kernels.set_busy()
    time.sleep(2.5)  # several cull windows
    assert C.STOP_ANNOTATION not in get_nb(cluster, "worker").metadata.annotations


def test_tpu_busy_blocks_cull_despite_idle_kernels(env):
    """The TPU-native signal: kernels idle, but the slice is training."""
    cluster, mgr, agents = env
    cluster.client.create(mk_nb("trainer", tpu=TPUSpec(accelerator="v5e", topology="2x2")))
    wait_for(lambda: "trainer-0" in agents, msg="pod up")
    agent = agents["trainer-0"]
    agent.kernels.set_idle(time.time() - 3600)  # kernels idle for an hour
    agent.monitor.duty = 0.9  # slice is hot
    agent.monitor.last_busy_ts = time.time()
    time.sleep(2.5)
    assert C.STOP_ANNOTATION not in get_nb(cluster, "trainer").metadata.annotations

    # slice cools down -> cull proceeds
    agent.monitor.duty = 0.0
    wait_for(
        lambda: C.STOP_ANNOTATION in get_nb(cluster, "trainer").metadata.annotations,
        msg="culled after TPU idle",
        timeout=15,
    )


def test_unstop_restarts_cull_cycle(env):
    cluster, mgr, agents = env
    cluster.client.create(mk_nb("cycle"))
    wait_for(
        lambda: C.STOP_ANNOTATION in get_nb(cluster, "cycle").metadata.annotations,
        msg="culled once",
    )
    old_handle = agents.get("cycle-0")

    # user restarts the notebook (dashboard removes the stop annotation).
    # Under an aggressive threshold the unstop can race the PREVIOUS cull's
    # still-pending scale-down: the old idle pod lingers Ready for a beat,
    # the culler legitimately re-culls within its (1 s) budget, and the
    # replacement never starts. That is configured-correct behavior — a
    # real user clicks restart again — so the test retries the unstop a
    # few times instead of requiring the first click to win the race. The
    # re-clicks are BOUNDED: each one must correspond to a real re-cull
    # race, so a persistently-lost unstop (a controller eating the patch)
    # fails the test loudly instead of hiding inside the retry loop.
    MAX_RECULL_CLICKS = 10
    clicks = 0

    def unstop():
        nonlocal clicks
        clicks += 1
        assert clicks <= MAX_RECULL_CLICKS, (
            f"unstop re-clicked {clicks}x: the stop annotation keeps "
            "returning — the unstop is being lost, not raced"
        )
        cluster.client.patch(
            Notebook, "user", "cycle",
            {"metadata": {"annotations": {C.STOP_ANNOTATION: None}}},
        )

    unstop()
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if agents.get("cycle-0") not in (None, old_handle):
            break
        if C.STOP_ANNOTATION in get_nb(cluster, "cycle").metadata.annotations:
            unstop()  # re-culled before the new pod arrived: click again
        time.sleep(0.1)
    assert agents.get("cycle-0") not in (None, old_handle), "new pod back"
    agents["cycle-0"].kernels.set_busy()
    # a cull decision already in flight when set_busy landed can still
    # write the stop annotation (same aggressive-threshold race as above):
    # keep clicking inside the wait — once probed busy it stays alive
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        agents["cycle-0"].kernels.set_busy()  # covers re-recreated agents too
        nb_now = get_nb(cluster, "cycle")
        if nb_now.status.ready_replicas == 1:
            break
        if C.STOP_ANNOTATION in nb_now.metadata.annotations:
            unstop()
        time.sleep(0.1)
    assert get_nb(cluster, "cycle").status.ready_replicas == 1, "ready again"
    time.sleep(1.0)
    assert C.STOP_ANNOTATION not in get_nb(cluster, "cycle").metadata.annotations


def test_probe_failure_defers_culling(env):
    """Jupyter probe unreachable -> check timestamp advances, no cull."""
    cluster, mgr, agents = env

    # a notebook whose pod serves nothing (no agent behavior matches)
    nb = Notebook()
    nb.metadata.name = "dark"
    nb.metadata.namespace = "other-ns"  # behavior keyed on label still matches...
    nb.spec.template.spec.containers = [Container(name="dark", image="jax:1")]
    # override: create in user ns but without agent by removing behavior match
    nb.metadata.namespace = "user"
    nb.metadata.labels["no-agent"] = "true"
    cluster.client.create(nb)
    # kubelet behavior serves an agent for every labeled pod; kill its server
    wait_for(
        lambda: cluster.kubelet.server_for("user", "dark-0") is not None,
        msg="server registered",
    )
    # stop the server so probes fail (DNS still resolves to a dead port)
    key = "user/dark-0"
    with cluster.kubelet._lock:
        entry = cluster.kubelet._servers.get(key)
    assert entry
    entry[3]()
    time.sleep(2.5)
    nb = get_nb(cluster, "dark")
    assert C.STOP_ANNOTATION not in nb.metadata.annotations
    assert C.LAST_ACTIVITY_CHECK_TIMESTAMP_ANNOTATION in nb.metadata.annotations



def test_culling_suspended_while_degraded_and_clock_resets_after_repair():
    """ISSUE 4 satellite: the idleness clock is SUSPENDED while a notebook is
    Degraded/mid-repair — a preempted notebook must not be culled for
    "idling" during its own recovery — and restarts from the repair's
    completion, so the notebook is (a) alive through a repair longer than the
    cull threshold, (b) not culled immediately after repair, (c) still
    cullable once genuinely idle afterwards."""
    from odh_kubeflow_tpu.api.notebook import TPUSpec
    from odh_kubeflow_tpu.controllers import (
        ProbeStatusController,
        SliceRepairController,
    )

    config = Config(
        enable_culling=True,
        cull_idle_time_min=1.5 / 60.0,  # 1.5 s idle threshold
        idleness_check_period_min=0.1 / 60.0,
        readiness_probe_period_s=0.1,
        checkpoint_window_s=3.0,  # repair window > cull threshold: the
        repair_backoff_s=0.3,     # suspension is what keeps it alive
        repair_backoff_max_s=0.6,
        repair_max_attempts=50,
    )
    cluster = SimCluster().start()
    cluster.add_tpu_pool("pool", "v5e", "2x2")  # ONE slice: repair must wait
    mgr = Manager(cluster.store)
    NotebookReconciler(mgr, config).setup()
    CullingReconciler(mgr, config, http_get=cluster.http_get).setup()
    ProbeStatusController(mgr, config, http_get=cluster.http_get).setup()
    SliceRepairController(mgr, config, http_get=cluster.http_get).setup()
    agents = {}
    # idle from the start: without the repair suspension this notebook gets
    # culled the moment the 1.5 s idle threshold lapses
    cluster.add_pod_behavior(
        sim_agent_behavior(agents, duty=0.0, kernels_busy=False, chips=4)
    )
    mgr.start()
    try:
        cluster.client.create(
            mk_nb("healing", tpu=TPUSpec(accelerator="v5e", topology="2x2"))
        )
        wait_for(
            lambda: get_nb(cluster, "healing").status.tpu is not None
            and get_nb(cluster, "healing").status.tpu.mesh_ready,
            msg="mesh ready",
        )
        # preempt the only node, long grace: the notebook sits Degraded (pods
        # still Ready, probes answering "idle") through the 3 s checkpoint
        # window — far past the 1.5 s cull threshold
        node = cluster.client.get(Pod, "user", "healing-0").spec.node_name
        cluster.preempt_node(node, grace_s=10.0)
        wait_for(
            lambda: C.TPU_REPAIR_STATE_ANNOTATION
            in get_nb(cluster, "healing").metadata.annotations,
            msg="repair began",
        )
        # (a) degraded far longer than the cull threshold: never culled
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            assert (
                C.STOP_ANNOTATION
                not in get_nb(cluster, "healing").metadata.annotations
            ), "culled mid-repair: the idleness clock was not suspended"
            time.sleep(0.1)
        # capacity returns; the gang re-places and the repair completes
        cluster.restore_node(node)
        wait_for(
            lambda: C.TPU_REPAIR_STATE_ANNOTATION
            not in get_nb(cluster, "healing").metadata.annotations
            and get_nb(cluster, "healing").status.tpu.mesh_ready,
            timeout=30,
            msg="repaired",
        )
        # (b) the clock restarted at completion: no instant cull
        nb = get_nb(cluster, "healing")
        assert C.STOP_ANNOTATION not in nb.metadata.annotations
        assert C.LAST_ACTIVITY_ANNOTATION in nb.metadata.annotations
        # (c) but a genuinely idle notebook is still culled afterwards
        wait_for(
            lambda: C.STOP_ANNOTATION
            in get_nb(cluster, "healing").metadata.annotations,
            timeout=20,
            msg="culled once idle after repair",
        )
    finally:
        mgr.stop()
        cluster.stop()


def test_resume_rearms_idle_clock_no_instant_recull():
    """ISSUE 7 satellite: a resumed notebook's idleness clock starts at
    RESUME time, not the preserved pre-suspend last-activity — else a
    just-resumed notebook is instantly re-culled — and the clock is
    suspended entirely while the resume is in flight. The notebook must be
    (a) resumable without an instant re-cull, and (b) still cullable (back
    into suspension) once genuinely idle afterwards."""
    from odh_kubeflow_tpu.api.notebook import TPUSpec
    from odh_kubeflow_tpu.controllers import (
        ProbeStatusController,
        SuspendResumeController,
    )

    config = Config(
        enable_culling=True,
        suspend_enabled=True,
        # a WIDE idle threshold: the "no instant re-cull" window below must
        # stay clear of the legitimate next cull even when a loaded suite
        # delays the resume-detection poll by a second or two
        cull_idle_time_min=4.0 / 60.0,  # 4.0 s idle threshold
        idleness_check_period_min=0.1 / 60.0,
        readiness_probe_period_s=0.1,
        suspend_checkpoint_window_s=0.5,
        resume_timeout_s=20.0,
        resume_max_attempts=4,
    )
    cluster = SimCluster().start()
    cluster.add_tpu_pool("pool", "v5e", "2x2")
    mgr = Manager(cluster.store)
    NotebookReconciler(mgr, config).setup()
    CullingReconciler(mgr, config, http_get=cluster.http_get).setup()
    ProbeStatusController(mgr, config, http_get=cluster.http_get).setup()
    SuspendResumeController(mgr, config, http_get=cluster.http_get).setup()
    agents = {}
    # idle from the start: the culler suspends the notebook on its own
    cluster.add_pod_behavior(
        sim_agent_behavior(agents, duty=0.0, kernels_busy=False, chips=4)
    )
    mgr.start()
    try:
        cluster.client.create(
            mk_nb("napper", tpu=TPUSpec(accelerator="v5e", topology="2x2"))
        )
        # culled INTO suspension (the culler's stop patch carries the
        # checkpointing stamp when suspend is enabled)
        wait_for(
            lambda: get_nb(cluster, "napper").metadata.annotations.get(
                C.TPU_SUSPEND_STATE_ANNOTATION
            ) == "suspended",
            timeout=20,
            msg="culled into Suspended",
        )
        # the poisoned clock: a preserved pre-suspend last-activity, hours
        # old (a culler that never got to remove it before the unstop)
        cluster.client.patch(
            Notebook, "user", "napper",
            {"metadata": {"annotations": {
                C.LAST_ACTIVITY_ANNOTATION: "2020-01-01T00:00:00Z",
            }}},
        )
        t_unstop = time.time()
        cluster.client.patch(
            Notebook, "user", "napper",
            {"metadata": {"annotations": {C.STOP_ANNOTATION: None}}},
        )
        # (a) resume completes — the mid-resume clock suspension means the
        # 2020 annotation never triggers a cull DURING the resume, and the
        # re-arm means none fires right after it either
        wait_for(
            lambda: not get_nb(cluster, "napper").metadata.annotations.get(
                C.TPU_SUSPEND_STATE_ANNOTATION
            )
            and get_nb(cluster, "napper").status.tpu is not None
            and get_nb(cluster, "napper").status.tpu.mesh_ready,
            timeout=30,
            msg="resumed",
        )
        assert C.STOP_ANNOTATION not in get_nb(
            cluster, "napper"
        ).metadata.annotations
        from odh_kubeflow_tpu.apimachinery import parse_time

        # wait_for, not a one-shot read: a culler removal patch from the
        # suspended phase can race just past the resume's re-arm; the next
        # culler pass re-initializes the annotation to now either way
        def rearmed():
            ts = get_nb(cluster, "napper").metadata.annotations.get(
                C.LAST_ACTIVITY_ANNOTATION
            )
            return bool(ts) and parse_time(ts).timestamp() >= t_unstop - 1.0

        wait_for(rearmed, timeout=10,
                 msg="idle clock re-armed from resume time")
        # no instant re-cull off stale state: survive well under a threshold
        deadline = time.monotonic() + 1.5
        while time.monotonic() < deadline:
            assert (
                C.STOP_ANNOTATION
                not in get_nb(cluster, "napper").metadata.annotations
            ), "re-culled instantly after resume"
            time.sleep(0.1)
        # (b) a genuinely idle notebook is still culled (re-suspended) later
        wait_for(
            lambda: C.STOP_ANNOTATION
            in get_nb(cluster, "napper").metadata.annotations,
            timeout=30,
            msg="culled again once genuinely idle",
        )
    finally:
        mgr.stop()
        cluster.stop()


def test_dev_mode_probes_through_local_proxy():
    """DEV mode (reference culling_controller.go:249-273): probes route
    through a localhost:8001 kubectl-proxy URL instead of the in-cluster
    service DNS name, so the culler is debuggable off-cluster. The proxy
    path targets the service's ACTUAL port name (http-notebook,
    notebook_controller.go:543) — the reference's format string interpolates
    http-{name} there, which its own service never defines."""
    from odh_kubeflow_tpu.controllers import Config
    from odh_kubeflow_tpu.controllers.culling import CullingReconciler

    nb = Notebook()
    nb.metadata.name = "my-nb"
    nb.metadata.namespace = "team-a"

    def make(dev: bool) -> str:
        rec = CullingReconciler.__new__(CullingReconciler)
        rec.config = Config()
        rec.config.dev_mode = dev
        return rec.jupyter_url(nb, "kernels")

    assert make(True) == (
        "http://localhost:8001/api/v1/namespaces/team-a/services/"
        "my-nb:http-notebook/proxy/notebook/team-a/my-nb/api/kernels"
    )
    # non-DEV: in-cluster service DNS, reference URL shape
    assert make(False) == (
        "http://my-nb.team-a.svc.cluster.local/notebook/team-a/my-nb/api/kernels"
    )


def test_dev_mode_env_flag():
    """DEV env var flips dev_mode exactly like the reference's GetEnvDefault
    (\"false\" default)."""
    import os

    from odh_kubeflow_tpu.controllers import Config

    old = os.environ.get("DEV")
    try:
        os.environ["DEV"] = "true"
        assert Config.from_env().dev_mode is True
        os.environ["DEV"] = "false"
        assert Config.from_env().dev_mode is False
    finally:
        if old is None:
            os.environ.pop("DEV", None)
        else:
            os.environ["DEV"] = old
