"""JAXGUARD runtime-twin contract tests (ISSUE 12).

The static jaxlint pass proves the SOURCE carries no retrace hazard or
hot-loop host sync; these tests prove the PROCESS guard catches the same
sins at runtime — and that it costs nothing when disarmed:

- a guarded region whose jit retraces past its declared compile budget
  raises CompileBudgetError at region exit;
- a device_get past an armed region's per-entry transfer budget raises
  HostTransferError BEFORE fetching, with the offending call site as the
  innermost user frame of the traceback;
- allow_transfer() is the audited runtime twin of the
  `# lint: disable=host-transfer` pragma;
- a donation the runtime silently ignores (un-aliasable output shape)
  raises DonationError, while an honored donation passes;
- the per-call audit stays under 10% overhead armed and the whole module
  is inert with JAXGUARD unset (same bar as the invcheck overhead test);
- the serving regression: at steady state the engine performs exactly ONE
  host sync per decode burst (the batched post-burst drain) and holds the
  declared burst compile budget.
"""
import time
import traceback
import warnings

import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from odh_kubeflow_tpu.analysis import hotregions
from odh_kubeflow_tpu.utils import jaxguard

pytestmark = pytest.mark.analysis


@pytest.fixture
def armed(monkeypatch):
    monkeypatch.setenv("JAXGUARD", "1")


# ---------------------------------------------------------------------------
# registry contract
# ---------------------------------------------------------------------------


def test_registry_rejects_unknown_region_names():
    with pytest.raises(KeyError):
        hotregions.get("serving.typo")
    with pytest.raises(KeyError):
        # a typo'd region fails at DECORATION time, not first dispatch
        jaxguard.jit(lambda x: x, region="serving.typo")  # lint: disable=retrace-hazard


def test_registry_declares_the_data_plane_regions():
    burst = hotregions.get("serving.decode_burst")
    assert burst.compile_budget == 2  # warmup + steady-state shapes
    assert burst.transfer_budget == 0  # steady state syncs NOTHING in-region
    assert hotregions.get("serving.prefill").transfer_budget == 1


# ---------------------------------------------------------------------------
# compile-count budget
# ---------------------------------------------------------------------------


def test_compile_counter_attributes_traces_always_even_unarmed():
    def mul(x, n):
        return x * n

    before = jaxguard.compile_count("bench.train_step")
    f = jaxguard.jit(mul, region="bench.train_step", static_argnums=(1,))
    f(jnp.ones(4), 2)
    f(jnp.ones(4), 2)  # cache hit: no trace
    f(jnp.ones(4), 3)  # new static value: retrace
    assert jaxguard.compile_count("bench.train_step") - before == 2


def test_compile_budget_breach_raises_at_region_exit(armed):
    def mul(x, n):
        return x * n

    f = jaxguard.jit(mul, region="bench.train_step", static_argnums=(1,))
    guard = jaxguard.region("bench.train_step")  # declared budget: 1
    with guard:
        f(jnp.ones(4), 2)  # one trace: within budget
    assert guard.compiles == 1
    with pytest.raises(jaxguard.CompileBudgetError, match="compile budget 1"):
        with guard:
            f(jnp.ones(4), 3)  # static value churns per call:
            f(jnp.ones(4), 4)  # the retrace leak the budget exists to catch


# ---------------------------------------------------------------------------
# transfer guard
# ---------------------------------------------------------------------------


def _offending_fetch(x):
    return jax.device_get(x)


_OFFENDING_LINE = _offending_fetch.__code__.co_firstlineno + 1


def test_transfer_in_zero_budget_region_raises_at_offending_line(armed):
    x = jnp.ones(3)
    with pytest.raises(jaxguard.HostTransferError) as excinfo:
        with jaxguard.region("serving.decode_burst"):  # transfer budget 0
            _offending_fetch(x)
    frames = traceback.extract_tb(excinfo.tb)
    ours = [f for f in frames if f.filename == _offending_fetch.__code__.co_filename]
    # innermost user frame is the device_get call site itself: the raise
    # happens BEFORE the fetch, inside the shim
    assert ours[-1].lineno == _OFFENDING_LINE


def test_transfer_budget_allows_the_declared_fetch_then_raises(armed):
    x = jnp.ones(3)
    with pytest.raises(jaxguard.HostTransferError):
        with jaxguard.region("serving.prefill"):  # transfer budget 1
            jax.device_get(x)  # the budgeted first-token fetch: fine
            jax.device_get(x)  # the second sync is the regression


def test_transfer_budget_is_per_entry_not_cumulative(armed):
    x = jnp.ones(3)
    guard = jaxguard.region("serving.prefill")
    for _ in range(3):
        with guard:
            jax.device_get(x)  # one per entry, every entry: within budget


def test_allow_transfer_is_the_runtime_pragma(armed):
    x = jnp.ones(3)
    with jaxguard.region("serving.decode_burst"):
        with jaxguard.allow_transfer():  # audited escape hatch
            jax.device_get(x)
    # outside the allow window the same call still raises
    with pytest.raises(jaxguard.HostTransferError):
        with jaxguard.region("serving.decode_burst"):
            jax.device_get(x)


def test_transfer_counter_visible_for_stats(armed):
    x = jnp.ones(3)
    before = jaxguard.transfer_count()
    with jaxguard.region("serving.prefill"):
        jax.device_get(x)
    assert jaxguard.transfer_count() == before + 1


# ---------------------------------------------------------------------------
# donation audit
# ---------------------------------------------------------------------------


def test_ignored_donation_raises_donation_error(armed):
    def shrink(x):
        return x[:1] * 2.0  # output cannot alias the donated input's buffer

    bad = jaxguard.jit(shrink, region="bench.train_step", donate_argnums=(0,))
    with warnings.catch_warnings():
        # jax itself warns "Some donated buffers were not usable" — the
        # audit turns exactly that condition into a hard error
        warnings.simplefilter("ignore")
        with pytest.raises(jaxguard.DonationError, match="NOT.*aliased"):
            bad(jnp.arange(8, dtype=jnp.float32))


def test_honored_donation_passes_and_input_is_recycled(armed):
    def bump(x):
        return x + 1.0  # same shape/dtype: XLA aliases in place

    good = jaxguard.jit(bump, region="bench.train_step", donate_argnums=(0,))
    x = jnp.arange(8, dtype=jnp.float32)
    out = good(x)
    assert x.is_deleted()  # the donation actually happened
    assert jax.device_get(out)[0] == 1.0


def test_donation_audit_inert_when_unarmed(monkeypatch):
    monkeypatch.delenv("JAXGUARD", raising=False)

    def shrink(x):
        return x[:1] * 2.0

    bad = jaxguard.jit(shrink, region="bench.train_step", donate_argnums=(0,))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        bad(jnp.arange(8, dtype=jnp.float32))  # no audit, no raise


# ---------------------------------------------------------------------------
# cost: <10% armed, inert off (the invcheck overhead bar)
# ---------------------------------------------------------------------------


def test_region_is_noop_when_unarmed(monkeypatch):
    monkeypatch.delenv("JAXGUARD", raising=False)
    guard = jaxguard.region("serving.decode_burst")
    with guard:
        jax.device_get(jnp.ones(2))  # zero-budget region, but guard is off
    assert guard.compiles == 0


def test_armed_donation_audit_overhead_under_ten_percent(armed):
    def bump(x):
        return x + 1.0

    plain = jax.jit(bump, donate_argnums=(0,))
    guarded = jaxguard.jit(bump, region="bench.train_step", donate_argnums=(0,))
    n = 200

    def run(fn):
        x = jnp.arange(64, dtype=jnp.float32)
        fn(x).block_until_ready()  # compile outside the timed window
        x = jnp.arange(64, dtype=jnp.float32)
        t0 = time.perf_counter()
        for _ in range(n):
            x = fn(x)
        x.block_until_ready()
        return (time.perf_counter() - t0) / n

    base = min(run(plain) for _ in range(3))
    armed_cost = min(run(guarded) for _ in range(3))
    added = armed_cost - base
    # same bar as the invcheck overhead test: 10% or an absolute floor that
    # absorbs scheduler noise on a loaded CI box
    assert added < max(0.10 * base, 0.0005), (
        f"donation audit adds {added * 1e6:.1f}us/call over {base * 1e6:.1f}us"
    )


# ---------------------------------------------------------------------------
# the serving steady-state regression (satellite 6)
# ---------------------------------------------------------------------------


def test_engine_steady_state_one_host_sync_per_burst(armed):
    """The engine bug this PR fixes: the steady-state loop used to drain
    five device values with five separate host syncs per burst. Under an
    armed guard the burst region (transfer budget 0) proves no in-region
    sync survives, and the post-burst drain is ONE batched device_get."""
    from odh_kubeflow_tpu.models import TransformerConfig, init_params
    from odh_kubeflow_tpu.serving.engine import ServingEngine

    cfg = TransformerConfig(
        vocab=97, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=128,
        max_seq=64, dtype=jnp.float32, use_flash=False, remat=False,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    eng = ServingEngine(params, cfg, max_slots=2, max_seq=64)
    handles = [eng.submit([1, 2, 3], max_new=6) for _ in range(3)]
    assert eng.run_until_idle(timeout=120)
    assert all(h.result == "ok" for h in handles)
    stats = eng.stats()
    assert stats["host_transfers_last_burst"] == 1, (
        "steady-state drain must be ONE batched device_get per burst"
    )
    burst = hotregions.get("serving.decode_burst")
    assert stats["decode_burst_recompiles"] <= burst.compile_budget
