"""Fault injection: the control plane converges under every injected fault
class (tier-1 deterministic subset) and under a combined seeded "bad day".

Every test scripts rules against the SimCluster's FaultInjector
(cluster/faults.py) — watch drops, 410 relists, 409 storms, 429 throttling,
webhook callout failures, kubelet crash-restarts, probe partitions — and
asserts the product invariants survive: Notebooks reach Ready, culling still
fires, no controller thread dies, and the runtime's resilience counters move
under injection (and stay flat without it).

Determinism: rules fire on call counts (seeded budgets for the bad-day run),
never wall-clock timers; the ci/faults.sh lane reruns this file in a stress
loop with PYTHONHASHSEED pinned.
"""
import threading
import time

import pytest

from odh_kubeflow_tpu.api.notebook import Notebook, TPUSpec
from odh_kubeflow_tpu.apimachinery import (
    AdmissionDeniedError,
    ConflictError,
    ForbiddenError,
    NotFoundError,
    TooManyRequestsError,
)
from odh_kubeflow_tpu.api.core import Container
from odh_kubeflow_tpu.cluster import FaultRule, SimCluster, seeded_bad_day
from odh_kubeflow_tpu.controllers import (
    Config,
    CullingReconciler,
    NotebookReconciler,
    ProbeStatusController,
    constants as C,
)
from odh_kubeflow_tpu.probe import sim_agent_behavior
from odh_kubeflow_tpu.runtime import Manager
from odh_kubeflow_tpu.runtime import metrics as rm

pytestmark = pytest.mark.faults

NS = "chaos"

FAST = Config(
    enable_culling=True,
    cull_idle_time_min=1.5 / 60.0,  # 1.5 s idle threshold
    idleness_check_period_min=0.1 / 60.0,  # 0.1 s cadence
    readiness_probe_period_s=0.15,
    probe_breaker_threshold=2,
    probe_breaker_cooldown_s=0.3,
)


class Counters:
    """Delta snapshot over the global resilience counters (shared registry:
    tests assert movement relative to their own start)."""

    SERIES = {
        "watch_restarts": lambda: rm.watch_restarts_total.value(kind="Notebook"),
        "relists": lambda: rm.relists_total.value(kind="Notebook"),
        "retries": lambda: rm.client_retries_total.value(cause="throttle"),
        "webhook_ignore": lambda: rm.webhook_dispatch_failures_total.value(policy="Ignore"),
        "webhook_fail": lambda: rm.webhook_dispatch_failures_total.value(policy="Fail"),
        "breaker_trips": lambda: rm.breaker_trips_total.value(),
        "fenced_writes": lambda: rm.fenced_writes_total.value(),
    }

    def __init__(self):
        self.start = {k: fn() for k, fn in self.SERIES.items()}

    def delta(self, key: str) -> float:
        return self.SERIES[key]() - self.start[key]


@pytest.fixture()
def env():
    cluster = SimCluster().start()
    # enough single-host slices that every test population (incl. the soak's
    # cumulative rounds) gang-schedules without queuing on capacity
    cluster.add_tpu_pool("pool", "v5e", "2x2", slices=8)
    cluster.add_cpu_pool("cpu", nodes=1)
    mgr = Manager(cluster.store)
    NotebookReconciler(mgr, FAST).setup()
    culler = CullingReconciler(mgr, FAST, http_get=cluster.http_get)
    culler.setup()
    ProbeStatusController(mgr, FAST, http_get=cluster.http_get).setup()
    agents = {}
    # kernels start BUSY: culling tests flip them idle explicitly, so fault
    # recovery is never masked by a concurrent cull
    cluster.add_pod_behavior(
        sim_agent_behavior(agents, duty=0.0, kernels_busy=True, chips=4)
    )
    mgr.start()
    yield cluster, mgr, agents, culler
    mgr.stop()
    cluster.stop()
    cluster.faults.clear()


def mk_nb(name, tpu=False):
    nb = Notebook()
    nb.metadata.name = name
    nb.metadata.namespace = NS
    nb.spec.template.spec.containers = [Container(name=name, image="jax:1")]
    if tpu:
        nb.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2")
    return nb


def wait_for(fn, timeout=20, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if fn():
                return True
        # transient injected faults may also hit the test's own reads: the
        # convergence poll rides them out like any other client would
        except (NotFoundError, TooManyRequestsError, ConflictError):
            pass
        time.sleep(0.05)
    raise AssertionError(f"timeout: {msg}")


def get_nb(cluster, name):
    return cluster.client.get(Notebook, NS, name)


def nb_ready(cluster, name):
    return get_nb(cluster, name).status.ready_replicas >= 1


def assert_healthy(mgr):
    """No controller worker thread died — the blanket invariant every fault
    class must preserve."""
    assert mgr.healthz(), "a controller thread died under fault injection"


def set_idle(agents, pod_name):
    agents[pod_name].kernels.set_idle(time.time() - 3600)


# ---------------------------------------------------------------------------
# fault-free path: the counters the other tests assert nonzero stay flat
# ---------------------------------------------------------------------------


def test_fault_free_path_keeps_counters_flat(env):
    cluster, mgr, agents, culler = env
    snap = Counters()
    cluster.client.create(mk_nb("calm", tpu=True))
    wait_for(lambda: nb_ready(cluster, "calm"), msg="calm ready")
    wait_for(
        lambda: (get_nb(cluster, "calm").status.tpu or None) is not None
        and get_nb(cluster, "calm").status.tpu.mesh_ready,
        msg="mesh ready",
    )
    for key in ("watch_restarts", "relists", "retries", "breaker_trips",
                "fenced_writes", "webhook_ignore", "webhook_fail"):
        assert snap.delta(key) == 0, f"{key} moved on the fault-free path"
    assert_healthy(mgr)


# ---------------------------------------------------------------------------
# watch drops + 410 relists
# ---------------------------------------------------------------------------


def test_watch_drops_recover_and_converge(env):
    cluster, mgr, agents, culler = env
    snap = Counters()
    cluster.client.create(mk_nb("dropper", tpu=True))
    # repeatedly sever every product watch while the notebook converges
    for _ in range(4):
        cluster.faults.drop_watches()
        time.sleep(0.15)
    wait_for(lambda: nb_ready(cluster, "dropper"), msg="ready despite drops")
    wait_for(
        lambda: (get_nb(cluster, "dropper").status.tpu or None) is not None
        and get_nb(cluster, "dropper").status.tpu.mesh_ready,
        msg="mesh ready despite drops",
    )
    assert snap.delta("watch_restarts") > 0, "informers must log restarts"
    # a fresh notebook created AFTER the drops still flows end-to-end
    cluster.client.create(mk_nb("after-drop"))
    wait_for(lambda: nb_ready(cluster, "after-drop"), msg="post-drop create")
    assert_healthy(mgr)


def test_410_relist_diffs_cache_and_converges(env):
    cluster, mgr, agents, culler = env
    snap = Counters()
    cluster.client.create(mk_nb("keeper"))
    cluster.client.create(mk_nb("goner"))
    wait_for(lambda: nb_ready(cluster, "keeper"), msg="keeper ready")
    wait_for(lambda: nb_ready(cluster, "goner"), msg="goner ready")

    # force the next Notebook watch resume to answer 410, then sever the
    # stream and delete a notebook while the watch is down: recovery must
    # come through relist+diff, with a synthetic DELETED for the goner
    # (times=1: the relist's own re-watch must succeed, or the informer
    # correctly falls back to yet another resume attempt instead)
    cluster.faults.expire_watch(kind="Notebook", times=1)
    cluster.faults.drop_watches(kind="Notebook")
    cluster.client.delete(Notebook, NS, "goner")

    inf = mgr.informers.peek("kubeflow.org/v1beta1", "Notebook")
    assert inf is not None
    wait_for(lambda: inf.get(NS, "goner") is None, msg="cache drops goner")
    assert inf.get(NS, "keeper") is not None, "cache keeps the keeper"
    assert snap.delta("relists") > 0, "recovery must go through relist"
    assert inf.synced.is_set(), "synced must survive a relist"
    # the cache keeps tracking post-relist events
    cluster.client.create(mk_nb("reborn"))
    wait_for(lambda: nb_ready(cluster, "reborn"), msg="post-relist create")
    assert_healthy(mgr)


# ---------------------------------------------------------------------------
# 409 conflict storms + 429 throttling
# ---------------------------------------------------------------------------


def test_conflict_storm_converges_and_culls(env):
    cluster, mgr, agents, culler = env
    rule = cluster.faults.conflict_storm("Notebook", times=8)
    cluster.client.create(mk_nb("stormy"))
    wait_for(lambda: nb_ready(cluster, "stormy"), msg="ready despite 409s")
    assert rule.fired > 0, "the storm must actually have hit writers"
    # culling still fires through its retry_on_conflict paths
    wait_for(lambda: "stormy-0" in agents, msg="agent up")
    set_idle(agents, "stormy-0")
    wait_for(
        lambda: C.STOP_ANNOTATION in get_nb(cluster, "stormy").metadata.annotations,
        msg="culled despite storm residue",
    )
    assert_healthy(mgr)


def test_429_throttle_is_honored_and_converges(env):
    cluster, mgr, agents, culler = env
    snap = Counters()
    # throttle everything but creates (the test's own create must enter the
    # system; controller traffic supplies plenty of throttled ops)
    cluster.faults.throttle(
        times=6, retry_after=0.02,
        match=lambda ctx: ctx.get("verb") != "create",
    )
    cluster.client.create(mk_nb("throttled", tpu=True))
    wait_for(lambda: nb_ready(cluster, "throttled"), msg="ready despite 429s")
    wait_for(
        lambda: (get_nb(cluster, "throttled").status.tpu or None) is not None
        and get_nb(cluster, "throttled").status.tpu.mesh_ready,
        msg="mesh ready despite 429s",
    )
    assert snap.delta("retries") > 0, "clients must retry with Retry-After"
    assert_healthy(mgr)


# ---------------------------------------------------------------------------
# webhook callout failures honor failurePolicy
# ---------------------------------------------------------------------------


def _webhook_config(store, name, policy):
    store.create_raw({
        "apiVersion": "admissionregistration.k8s.io/v1",
        "kind": "MutatingWebhookConfiguration",
        "metadata": {"name": name},
        "webhooks": [{
            "name": f"{name}.kubeflow.org",
            "failurePolicy": policy,
            "clientConfig": {"url": "http://127.0.0.1:9/mutate"},  # dead port
            "rules": [{
                "operations": ["CREATE", "UPDATE"],
                "apiGroups": ["kubeflow.org"],
                "apiVersions": ["*"],
                "resources": ["notebooks"],
            }],
        }],
    })


def test_webhook_outage_respects_failure_policy():
    from odh_kubeflow_tpu.cluster import FaultInjector
    from odh_kubeflow_tpu.cluster.store import Store
    from odh_kubeflow_tpu.cluster.webhook_dispatch import WebhookDispatcher

    snap = Counters()
    inj = FaultInjector()
    store = Store(faults=inj)
    disp = WebhookDispatcher(store)
    nb = {"apiVersion": "kubeflow.org/v1", "kind": "Notebook",
          "metadata": {"name": "n", "namespace": NS}}

    # failurePolicy=Ignore: an injected timeout must NOT block the write
    _webhook_config(store, "ignore-hook", "Ignore")
    inj.webhook_outage(times=1, mode="timeout")
    out = disp("CREATE", dict(nb), None)
    assert out["metadata"]["name"] == "n"
    assert snap.delta("webhook_ignore") == 1

    # failurePolicy=Fail: the injected failure rejects the write
    store.delete_raw("admissionregistration.k8s.io/v1",
                     "MutatingWebhookConfiguration", "", "ignore-hook")
    _webhook_config(store, "fail-hook", "Fail")
    inj.webhook_outage(times=1, mode="error")
    with pytest.raises(AdmissionDeniedError):
        disp("CREATE", dict(nb), None)
    assert snap.delta("webhook_fail") == 1

    # outage over (rule exhausted, but the URL is genuinely dead): Fail
    # still rejects — the dispatcher treats injected and real failures alike
    with pytest.raises(AdmissionDeniedError):
        disp("CREATE", dict(nb), None)


# ---------------------------------------------------------------------------
# kubelet crash-restarts
# ---------------------------------------------------------------------------


def test_kubelet_crash_restart_recovers(env):
    from odh_kubeflow_tpu.api.core import Pod

    cluster, mgr, agents, culler = env
    cluster.client.create(mk_nb("phoenix", tpu=True))
    wait_for(lambda: nb_ready(cluster, "phoenix"), msg="first bring-up")

    old_agent = agents.get("phoenix-0")
    cluster.faults.crash_pod("phoenix-0", restarts=2)
    # poke the steady-state pod so the kubelet reconciles (a real crash
    # would surface as a container-runtime event; the sim's crash verdict
    # is consulted at reconcile time)
    from odh_kubeflow_tpu.api.core import Pod as PodKind

    cluster.client.patch(
        PodKind, NS, "phoenix-0", {"metadata": {"annotations": {"chaos": "1"}}}
    )
    # the crash must be observable: container not-ready with a bumped
    # restartCount...
    wait_for(
        lambda: any(
            s.restart_count >= 1
            for s in cluster.client.get(Pod, NS, "phoenix-0").status.container_statuses
        ),
        msg="restartCount bumped",
    )
    # ...and the pod must come back Ready with a FRESH probe agent (the old
    # one's close() is permanent; its port-0 sentinel must not be probed)
    wait_for(
        lambda: cluster.client.get(Pod, NS, "phoenix-0").is_ready(),
        msg="pod recovered",
    )
    wait_for(
        lambda: agents.get("phoenix-0") is not old_agent,
        msg="fresh agent incarnation",
    )
    wait_for(lambda: nb_ready(cluster, "phoenix"), msg="notebook recovered")
    wait_for(
        lambda: (get_nb(cluster, "phoenix").status.tpu or None) is not None
        and get_nb(cluster, "phoenix").status.tpu.mesh_ready,
        msg="mesh ready after crash-restart",
    )
    assert_healthy(mgr)


def test_closed_agent_serves_port_zero_sentinel():
    """probe/agent.py satellite: serve() on a closed agent must answer with
    the explicit port-0 sentinel, never a stale (OS-reusable) port."""
    from odh_kubeflow_tpu.probe.agent import NotebookAgent, SimTPUMonitor

    agent = NotebookAgent(monitor=SimTPUMonitor())
    host, port, close = agent.serve()
    assert port > 0
    agent.close()
    host2, port2, _ = agent.serve()
    assert port2 == 0, "closed agent must return the port-0 sentinel"


# ---------------------------------------------------------------------------
# probe partitions trip the breaker; culling survives and resumes
# ---------------------------------------------------------------------------


def test_probe_partition_trips_breaker_then_culling_resumes(env):
    cluster, mgr, agents, culler = env
    snap = Counters()
    cluster.client.create(mk_nb("dark"))
    wait_for(lambda: nb_ready(cluster, "dark"), msg="ready")
    wait_for(lambda: "dark-0" in agents, msg="agent up")

    # partition the notebook's probe traffic FIRST (so the idle flip below
    # can never race a successful probe into an early cull), then go idle:
    # the culler must trip its breaker instead of hammering the dead route
    rule = cluster.faults.partition_probe(host="dark")
    set_idle(agents, "dark-0")
    wait_for(
        lambda: culler.breaker.is_open(f"{NS}/dark"),
        msg="breaker opens on repeated probe failures",
    )
    assert snap.delta("breaker_trips") >= 1
    assert C.STOP_ANNOTATION not in get_nb(cluster, "dark").metadata.annotations, (
        "an unprobeable notebook must never be culled"
    )

    # partition heals: the half-open trial succeeds, probing resumes, and
    # the (idle) notebook is finally culled
    cluster.faults.remove(rule)
    wait_for(
        lambda: C.STOP_ANNOTATION in get_nb(cluster, "dark").metadata.annotations,
        msg="culled after the partition heals",
        timeout=30,
    )
    assert_healthy(mgr)


# ---------------------------------------------------------------------------
# leader-election fencing
# ---------------------------------------------------------------------------


def test_partitioned_ex_leader_is_fenced(env):
    cluster, mgr, agents, culler = env
    snap = Counters()
    store = cluster.store

    mgr_a = Manager(store, leader_election=True, leader_election_id="fence-test")
    mgr_b = Manager(store, leader_election=True, leader_election_id="fence-test")
    for m in (mgr_a, mgr_b):
        m.elector.lease_duration = 1.0
        m.elector.renew_period = 0.15

    try:
        mgr_a.start(wait_for_leadership_timeout=5)
        assert mgr_a.elector.is_leader.is_set()

        b_started = threading.Thread(
            target=lambda: mgr_b.start(wait_for_leadership_timeout=30),
            daemon=True,
        )
        b_started.start()
        time.sleep(0.3)
        assert not mgr_b.elector.is_leader.is_set(), "B must wait out A's lease"

        # partition A from the apiserver for LEASE WRITES: its renewals fail
        # while B's (writing holderIdentity=B) pass
        a_id = mgr_a.elector.identity
        cluster.faults.add(FaultRule(
            site="store.write",
            kind="Lease",
            error=lambda: ConnectionError("injected apiserver partition"),
            match=lambda ctx: (ctx.get("obj") or {}).get("spec", {}).get(
                "holderIdentity") == a_id,
        ))

        # A must stand down once its lease lapses...
        wait_for(
            lambda: not mgr_a.elector.is_leader.is_set(),
            msg="A stands down after lease lapse",
        )
        # ...and its writes are fenced from that moment on
        with pytest.raises(ForbiddenError):
            mgr_a.client.create(mk_nb("from-the-dead"))
        assert snap.delta("fenced_writes") >= 1
        with pytest.raises(NotFoundError):
            cluster.client.get(Notebook, NS, "from-the-dead")

        # B takes over once the stale lease ages out
        wait_for(
            lambda: mgr_b.elector.is_leader.is_set(),
            msg="B acquires leadership",
        )
        b_started.join(timeout=10)
    finally:
        cluster.faults.clear()
        mgr_a.stop()
        mgr_b.stop()


# ---------------------------------------------------------------------------
# the combined seeded schedule
# ---------------------------------------------------------------------------


def _bad_day(env, seed, notebooks, drops=3):
    """One deterministic bad day: seeded rule budgets + counted watch drops
    while `notebooks` converge; one of them is then culled on idleness."""
    cluster, mgr, agents, culler = env
    seeded_bad_day(cluster.faults, seed=seed)
    for name, tpu in notebooks:
        cluster.client.create(mk_nb(name, tpu=tpu))
    for _ in range(drops):
        cluster.faults.drop_watches()
        time.sleep(0.2)
    for name, tpu in notebooks:
        wait_for(lambda n=name: nb_ready(cluster, n), timeout=30,
                 msg=f"{name} ready through the bad day")
        if tpu:
            wait_for(
                lambda n=name: (get_nb(cluster, n).status.tpu or None) is not None
                and get_nb(cluster, n).status.tpu.mesh_ready,
                timeout=30,
                msg=f"{name} mesh ready through the bad day",
            )
    # culling still works at the end of the day
    victim = notebooks[0][0]
    wait_for(lambda: f"{victim}-0" in agents, msg="victim agent")
    set_idle(agents, f"{victim}-0")
    wait_for(
        lambda: C.STOP_ANNOTATION in get_nb(cluster, victim).metadata.annotations,
        timeout=30,
        msg="culling still fires after the bad day",
    )
    assert_healthy(mgr)


def test_seeded_bad_day_converges(env):
    cluster, mgr, agents, culler = env
    snap = Counters()
    _bad_day(env, seed=0xBAD_DA4, notebooks=[("bd-0", False), ("bd-1", True),
                                             ("bd-2", False)])
    assert snap.delta("watch_restarts") > 0
    # the seeded schedule includes throttle rules; conflict rules are
    # asserted via their fired counts
    fired = {r.site: r.fired for r in cluster.faults.rules()}
    assert fired.get("store.write", 0) > 0, "seeded 409 storm never fired"


@pytest.mark.slow
def test_chaos_soak_repeated_bad_days(env):
    """Soak: several consecutive seeded bad days over a growing population —
    every round must converge and cull, with no controller thread loss."""
    cluster, mgr, agents, culler = env
    for round_no, seed in enumerate((101, 202, 303)):
        cluster.faults.clear()
        names = [(f"soak-{round_no}-{i}", i % 2 == 1) for i in range(4)]
        _bad_day(env, seed=seed, notebooks=names, drops=5)
