"""Mutating webhook: lock injection, TPU validation, image catalog, CA
bundle, auth sidecar, update-blocking (the reference's subtlest behavior)."""
import json

import pytest

from odh_kubeflow_tpu.api.core import ConfigMap, Container, Secret
from odh_kubeflow_tpu.api.notebook import Notebook, TPUSpec
from odh_kubeflow_tpu.apimachinery import AdmissionDeniedError
from odh_kubeflow_tpu.cluster import Client, Store
from odh_kubeflow_tpu.controllers import Config, constants as C
from odh_kubeflow_tpu.controllers.webhook import (
    AUTH_PROXY_CONTAINER,
    CA_BUNDLE_CONFIGMAP,
    IMAGE_CATALOG_CONFIGMAP,
    NotebookWebhook,
)


@pytest.fixture()
def env():
    store = Store()
    client = Client(store)
    config = Config(controller_namespace="ctrl-ns")
    NotebookWebhook(client, config).register(store)
    return store, client, config


def mk_nb(name="nb", ns="user", image="base:1", tpu=None, annotations=None):
    nb = Notebook()
    nb.metadata.name = name
    nb.metadata.namespace = ns
    nb.metadata.annotations = dict(annotations or {})
    nb.spec.template.spec.containers = [Container(name=name, image=image)]
    if tpu:
        nb.spec.tpu = tpu
    return nb


def test_create_injects_lock(env):
    store, client, _ = env
    created = client.create(mk_nb())
    assert created.metadata.annotations[C.STOP_ANNOTATION] == C.RECONCILIATION_LOCK_VALUE


def test_invalid_tpu_rejected_at_admission(env):
    store, client, _ = env
    with pytest.raises(AdmissionDeniedError, match="spec.tpu invalid"):
        client.create(mk_nb(tpu=TPUSpec(accelerator="v5p", topology="3x5")))
    with pytest.raises(AdmissionDeniedError, match="runtime"):
        client.create(mk_nb(tpu=TPUSpec(accelerator="v5e", topology="2x2", runtime="cuda")))


def test_image_resolved_from_catalog(env):
    store, client, _ = env
    catalog = ConfigMap()
    catalog.metadata.name = IMAGE_CATALOG_CONFIGMAP
    catalog.metadata.namespace = "ctrl-ns"
    catalog.data = {"jax-notebook:2026a": "gcr.io/wb/jax-notebook@sha256:abc"}
    client.create(catalog)
    created = client.create(
        mk_nb(annotations={C.IMAGE_SELECTION_ANNOTATION: "jax-notebook:2026a"})
    )
    assert created.spec.template.spec.containers[0].image == "gcr.io/wb/jax-notebook@sha256:abc"


def test_missing_catalog_selection_keeps_image(env):
    store, client, _ = env
    created = client.create(
        mk_nb(annotations={C.IMAGE_SELECTION_ANNOTATION: "ghost:1"})
    )
    assert created.spec.template.spec.containers[0].image == "base:1"


def test_ca_bundle_mounted_when_present(env):
    store, client, _ = env
    cm = ConfigMap()
    cm.metadata.name = CA_BUNDLE_CONFIGMAP
    cm.metadata.namespace = "user"
    cm.data = {"ca-bundle.crt": "-----BEGIN CERTIFICATE-----..."}
    client.create(cm)
    created = client.create(mk_nb())
    podspec = created.spec.template.spec
    assert podspec.volume("trusted-ca") is not None
    c = podspec.containers[0]
    assert any(m.name == "trusted-ca" for m in c.volume_mounts)
    assert c.env_dict()["SSL_CERT_FILE"].endswith("ca-bundle.crt")


def test_auth_sidecar_injection_and_removal(env):
    store, client, _ = env
    created = client.create(mk_nb(annotations={C.INJECT_AUTH_ANNOTATION: "true"}))
    names = [c.name for c in created.spec.template.spec.containers]
    assert AUTH_PROXY_CONTAINER in names
    sidecar = created.spec.template.spec.container(AUTH_PROXY_CONTAINER)
    assert sidecar.resources.requests["cpu"] == "100m"
    assert created.spec.template.spec.volume("kube-rbac-proxy-config") is not None

    # switch auth off (stopped notebook so update-blocking doesn't interfere)
    nb = client.get(Notebook, "user", "nb")
    nb.metadata.annotations.pop(C.INJECT_AUTH_ANNOTATION)
    nb = client.update(nb)
    assert AUTH_PROXY_CONTAINER not in [c.name for c in nb.spec.template.spec.containers]


def test_auth_sidecar_resource_annotation_validated(env):
    store, client, _ = env
    with pytest.raises(AdmissionDeniedError, match="invalid resource quantity"):
        client.create(
            mk_nb(
                annotations={
                    C.INJECT_AUTH_ANNOTATION: "true",
                    C.AUTH_SIDECAR_CPU_REQUEST_ANNOTATION: "lots",
                }
            )
        )


def test_auth_sidecar_resource_annotation_applied(env):
    store, client, _ = env
    created = client.create(
        mk_nb(
            annotations={
                C.INJECT_AUTH_ANNOTATION: "true",
                C.AUTH_SIDECAR_CPU_LIMIT_ANNOTATION: "250m",
                C.AUTH_SIDECAR_MEMORY_LIMIT_ANNOTATION: "128Mi",
            }
        )
    )
    sidecar = created.spec.template.spec.container(AUTH_PROXY_CONTAINER)
    assert sidecar.resources.limits == {"cpu": "250m", "memory": "128Mi"}


def test_update_blocking_webhook_only_drift(env):
    """A running notebook must not restart because the catalog image moved:
    podspec reverts and update-pending records the first diff."""
    store, client, _ = env
    catalog = ConfigMap()
    catalog.metadata.name = IMAGE_CATALOG_CONFIGMAP
    catalog.metadata.namespace = "ctrl-ns"
    catalog.data = {"jax:1": "registry/jax:v1"}
    client.create(catalog)
    client.create(mk_nb(annotations={C.IMAGE_SELECTION_ANNOTATION: "jax:1"}))

    # notebook starts running: lock removed (extension controller's job)
    client.patch(Notebook, "user", "nb", {"metadata": {"annotations": {C.STOP_ANNOTATION: None}}})

    # catalog moves the tag
    cur = client.get(ConfigMap, "ctrl-ns", IMAGE_CATALOG_CONFIGMAP)
    cur.data["jax:1"] = "registry/jax:v2"
    client.update(cur)

    # a user touches only metadata (labels) -> webhook re-resolves the image,
    # but the update must NOT roll the pod
    nb = client.get(Notebook, "user", "nb")
    nb.metadata.labels["team"] = "ml"
    updated = client.update(nb)
    assert updated.spec.template.spec.containers[0].image == "registry/jax:v1"
    pending = updated.metadata.annotations[C.UPDATE_PENDING_ANNOTATION]
    assert "registry/jax:v1" in pending and "registry/jax:v2" in pending

    # the user themselves changes the podspec -> restart allowed; the webhook
    # still re-resolves the image from the (unchanged) selection annotation,
    # so the new catalog target lands (reference SetContainerImageFromRegistry
    # runs on every admission)
    nb = client.get(Notebook, "user", "nb")
    nb.spec.template.spec.containers[0].image = "custom/override:3"
    updated = client.update(nb)
    assert updated.spec.template.spec.containers[0].image == "registry/jax:v2"
    assert C.UPDATE_PENDING_ANNOTATION not in updated.metadata.annotations

    # dropping the selection annotation gives the user full image control
    nb = client.get(Notebook, "user", "nb")
    del nb.metadata.annotations[C.IMAGE_SELECTION_ANNOTATION]
    nb.spec.template.spec.containers[0].image = "custom/override:3"
    updated = client.update(nb)
    assert updated.spec.template.spec.containers[0].image == "custom/override:3"


def test_update_applies_when_stopped(env):
    store, client, _ = env
    catalog = ConfigMap()
    catalog.metadata.name = IMAGE_CATALOG_CONFIGMAP
    catalog.metadata.namespace = "ctrl-ns"
    catalog.data = {"jax:1": "registry/jax:v1"}
    client.create(catalog)
    client.create(mk_nb(annotations={C.IMAGE_SELECTION_ANNOTATION: "jax:1"}))
    # still locked (= stopped): catalog moves, update flows through freely
    cur = client.get(ConfigMap, "ctrl-ns", IMAGE_CATALOG_CONFIGMAP)
    cur.data["jax:1"] = "registry/jax:v2"
    client.update(cur)
    nb = client.get(Notebook, "user", "nb")
    nb.metadata.labels["x"] = "y"
    updated = client.update(nb)
    assert updated.spec.template.spec.containers[0].image == "registry/jax:v2"
    assert C.UPDATE_PENDING_ANNOTATION not in updated.metadata.annotations


def test_proxy_env_injection():
    store = Store()
    client = Client(store)
    config = Config(controller_namespace="ctrl-ns", inject_cluster_proxy_env=True)
    NotebookWebhook(client, config).register(store)
    cm = ConfigMap()
    cm.metadata.name = "cluster-proxy-config"
    cm.metadata.namespace = "ctrl-ns"
    cm.data = {"httpProxy": "http://proxy:3128", "noProxy": ".cluster.local"}
    client.create(cm)
    created = client.create(mk_nb())
    env_d = created.spec.template.spec.containers[0].env_dict()
    assert env_d["HTTP_PROXY"] == "http://proxy:3128"
    assert env_d["no_proxy"] == ".cluster.local"
    assert "HTTPS_PROXY" not in env_d


def test_proxy_env_user_lowercase_wins():
    """A user-set lowercase proxy var must not be clobbered by injection
    (set_env matches exact names, so writing either case would shadow it)."""
    store = Store()
    client = Client(store)
    config = Config(controller_namespace="ctrl-ns", inject_cluster_proxy_env=True)
    NotebookWebhook(client, config).register(store)
    cm = ConfigMap()
    cm.metadata.name = "cluster-proxy-config"
    cm.metadata.namespace = "ctrl-ns"
    cm.data = {"httpProxy": "http://cluster:3128"}
    client.create(cm)
    nb = mk_nb()
    nb.spec.template.spec.containers[0].set_env("http_proxy", "http://corp:8080")
    created = client.create(nb)
    env_d = created.spec.template.spec.containers[0].env_dict()
    assert env_d["http_proxy"] == "http://corp:8080"
    assert "HTTP_PROXY" not in env_d


def test_feast_config_mounted_by_label_and_unmounted_on_removal(env):
    store, client, _ = env
    from odh_kubeflow_tpu.controllers.webhook import FEAST_MOUNT_PATH, FEAST_VOLUME

    nb = mk_nb("feasty")
    nb.metadata.labels[C.FEAST_LABEL] = "true"
    created = client.create(nb)
    podspec = created.spec.template.spec
    assert podspec.volume(FEAST_VOLUME) is not None
    assert podspec.volume(FEAST_VOLUME).config_map["name"] == "feasty-feast-config"
    mounts = [m for m in podspec.containers[0].volume_mounts if m.name == FEAST_VOLUME]
    assert mounts and mounts[0].mount_path == FEAST_MOUNT_PATH

    # label removed -> webhook unmounts on the next update
    created.metadata.labels.pop(C.FEAST_LABEL)
    updated = client.update(created)
    podspec = updated.spec.template.spec
    assert podspec.volume(FEAST_VOLUME) is None
    assert all(m.name != FEAST_VOLUME for m in podspec.containers[0].volume_mounts)


def test_feast_mount_idempotent(env):
    store, client, _ = env
    from odh_kubeflow_tpu.controllers.webhook import FEAST_VOLUME

    nb = mk_nb("feast2")
    nb.metadata.labels[C.FEAST_LABEL] = "true"
    created = client.create(nb)
    updated = client.update(created)  # webhook runs again on UPDATE
    podspec = updated.spec.template.spec
    assert len([v for v in podspec.volumes if v.name == FEAST_VOLUME]) == 1
    assert len(
        [m for m in podspec.containers[0].volume_mounts if m.name == FEAST_VOLUME]
    ) == 1


def test_feast_legacy_volume_migrated_user_volume_kept(env):
    """Specs admitted under the pre-rename volume name 'feast-config' are
    migrated, but only when the volume is identifiably ours; a user volume
    sharing the generic name is never touched."""
    store, client, _ = env
    from odh_kubeflow_tpu.api.core import Volume, VolumeMount
    from odh_kubeflow_tpu.controllers.webhook import FEAST_VOLUME

    nb = mk_nb("legacy")
    nb.metadata.labels[C.FEAST_LABEL] = "true"
    # simulate a spec mutated by the old webhook: legacy name, our ConfigMap
    nb.spec.template.spec.volumes.append(
        Volume(name="feast-config", config_map={"name": "legacy-feast-config"})
    )
    nb.spec.template.spec.containers[0].volume_mounts.append(
        VolumeMount(name="feast-config", mount_path="/opt/app-root/src/feast-config")
    )
    # plus a genuinely user-owned volume with the generic name pattern
    nb.spec.template.spec.volumes.append(
        Volume(name="feast-config-user", config_map={"name": "my-own-cm"})
    )
    created = client.create(nb)
    podspec = created.spec.template.spec
    assert podspec.volume("feast-config") is None  # legacy migrated away
    assert podspec.volume(FEAST_VOLUME) is not None  # re-mounted under new name
    assert podspec.volume("feast-config-user") is not None  # user volume kept
    paths = [m.mount_path for m in podspec.containers[0].volume_mounts]
    assert paths.count("/opt/app-root/src/feast-config") == 1  # no duplicate mountPath


def test_feast_legacy_volume_not_ours_untouched(env):
    store, client, _ = env
    from odh_kubeflow_tpu.api.core import Volume

    nb = mk_nb("legacy2")
    # no feast label; a user volume named 'feast-config' backed by their own CM
    nb.spec.template.spec.volumes.append(
        Volume(name="feast-config", config_map={"name": "users-own-feast"})
    )
    created = client.create(nb)
    assert created.spec.template.spec.volume("feast-config") is not None


def test_feast_legacy_optional_volume_keeps_optionality(env):
    """Migration must not retroactively tighten optional->required: a legacy
    notebook whose ConfigMap never existed kept starting because the volume
    was optional; the migrated volume preserves that source verbatim."""
    store, client, _ = env
    from odh_kubeflow_tpu.api.core import Volume
    from odh_kubeflow_tpu.controllers.webhook import FEAST_VOLUME

    nb = mk_nb("legacy3")
    nb.metadata.labels[C.FEAST_LABEL] = "true"
    nb.spec.template.spec.volumes.append(
        Volume(
            name="feast-config",
            config_map={"name": "legacy3-feast-config", "optional": True},
        )
    )
    created = client.create(nb)
    vol = created.spec.template.spec.volume(FEAST_VOLUME)
    assert vol is not None and vol.config_map.get("optional") is True


# ---- pipeline runtime-images + Elyra mounts (VERDICT-r1 next #6) ----


def _mk_runtime_source(ns):
    cm = ConfigMap()
    cm.metadata.name = "runtime-sources"
    cm.metadata.namespace = ns
    cm.metadata.labels = {C.RUNTIME_IMAGE_LABEL: "true"}
    cm.data = {
        "Tensorflow 2.x": json.dumps({"display_name": "Tensorflow 2.x", "metadata": {"image_name": "tf:2"}})
    }
    return cm


def test_webhook_syncs_and_mounts_runtime_images():
    """reference notebook_webhook.go:400-410 + notebook_runtime.go:216-285:
    admission syncs the catalog into the user ns and mounts it at the
    pipeline-runtimes path in ALL containers."""
    from odh_kubeflow_tpu.controllers.extension import RUNTIME_IMAGES_CONFIGMAP
    from odh_kubeflow_tpu.controllers.webhook import (
        RUNTIME_IMAGES_MOUNT_PATH,
        RUNTIME_IMAGES_VOLUME,
    )

    store = Store()
    config = Config(controller_namespace="ctrl-ns")
    client = Client(store)
    client.create(_mk_runtime_source("ctrl-ns"))
    NotebookWebhook(client, config).register(store)

    nb = mk_nb("pipe")
    nb.spec.template.spec.containers.append(Container(name="sidecar", image="s:1"))
    out = client.create(nb)

    catalog = client.get(ConfigMap, "user", RUNTIME_IMAGES_CONFIGMAP)
    assert "tensorflow_2.x.json" in catalog.data
    spec = out.spec.template.spec
    vol = spec.volume(RUNTIME_IMAGES_VOLUME)
    assert vol is not None and vol.config_map == {"name": RUNTIME_IMAGES_CONFIGMAP}
    for c in spec.containers:
        mounts = {m.name: m for m in c.volume_mounts}
        assert RUNTIME_IMAGES_VOLUME in mounts
        assert mounts[RUNTIME_IMAGES_VOLUME].mount_path == RUNTIME_IMAGES_MOUNT_PATH
        assert mounts[RUNTIME_IMAGES_VOLUME].read_only is True


def test_webhook_no_catalog_no_mount():
    store = Store()
    client = Client(store)
    NotebookWebhook(client, Config(controller_namespace="ctrl-ns")).register(store)
    out = client.create(mk_nb("bare"))
    from odh_kubeflow_tpu.controllers.webhook import RUNTIME_IMAGES_VOLUME

    assert out.spec.template.spec.volume(RUNTIME_IMAGES_VOLUME) is None


def test_webhook_mounts_elyra_config_from_dspa():
    """DSPA-shaped extraction (reference notebook_dspa_secret.go:106-148,
    189-273): endpoints from the DSPA CR, creds from its S3 secret, public
    endpoint from the Gateway hostname; secret mounted at
    /opt/app-root/runtimes in all containers and owned by the DSPA."""
    from odh_kubeflow_tpu.api.dspa import (
        DataSciencePipelinesApplication,
        DSPASpec,
        ExternalStorage,
        ObjectStorage,
        S3CredentialsSecret,
    )
    from odh_kubeflow_tpu.api.gateway import (
        Gateway,
        GatewayListener,
        GatewaySpec,
    )
    from odh_kubeflow_tpu.controllers.extension import ELYRA_SECRET_NAME
    from odh_kubeflow_tpu.controllers.webhook import ELYRA_MOUNT_PATH, ELYRA_VOLUME

    store = Store()
    config = Config(
        controller_namespace="ctrl-ns",
        set_pipeline_secret=True,
        gateway_name="data-science-gateway",
        gateway_namespace="gw-ns",
    )
    client = Client(store)

    s3 = Secret()
    s3.metadata.name = "minio-creds"
    s3.metadata.namespace = "user"
    s3.string_data = {"accesskey": "AKIA", "secretkey": "hunter2"}
    client.create(s3)

    dspa = DataSciencePipelinesApplication()
    dspa.metadata.name = "dspa"
    dspa.metadata.namespace = "user"
    dspa.spec = DSPASpec(
        object_storage=ObjectStorage(
            external_storage=ExternalStorage(
                host="minio.user.svc:9000",
                scheme="http",
                bucket="pipelines",
                s3_credentials_secret=S3CredentialsSecret(
                    secret_name="minio-creds",
                    access_key="accesskey",
                    secret_key="secretkey",
                ),
            )
        )
    )
    client.create(dspa)

    gw = Gateway()
    gw.metadata.name = "data-science-gateway"
    gw.metadata.namespace = "gw-ns"
    gw.spec = GatewaySpec(listeners=[GatewayListener(name="https", hostname="ds.example.com")])
    client.create(gw)

    NotebookWebhook(client, config).register(store)
    out = client.create(mk_nb("ds"))

    secret = client.get(Secret, "user", ELYRA_SECRET_NAME)
    cfg = json.loads(secret.string_data["odh_dsp.json"])
    md = cfg["metadata"]
    assert md["api_endpoint"] == "https://ds-pipeline-dspa.user.svc.cluster.local:8443"
    assert md["public_api_endpoint"] == "https://ds.example.com/pipeline/user/dspa"
    assert md["cos_endpoint"] == "http://minio.user.svc:9000"
    assert md["cos_bucket"] == "pipelines"
    assert md["cos_username"] == "AKIA" and md["cos_password"] == "hunter2"
    assert any(r.name == "dspa" for r in secret.metadata.owner_references)

    spec = out.spec.template.spec
    vol = spec.volume(ELYRA_VOLUME)
    assert vol is not None and vol.secret == {"secretName": ELYRA_SECRET_NAME}
    assert all(
        any(m.name == ELYRA_VOLUME and m.mount_path == ELYRA_MOUNT_PATH for m in c.volume_mounts)
        for c in spec.containers
    )


def test_elyra_flat_secret_fallback_still_works():
    """No DSPA in the namespace: the flat pipeline-server-config path
    (round-1 behavior) still renders the secret."""
    from odh_kubeflow_tpu.controllers.extension import (
        ELYRA_SECRET_NAME,
        PIPELINE_SERVER_SECRET,
    )

    store = Store()
    config = Config(controller_namespace="ctrl-ns", set_pipeline_secret=True)
    client = Client(store)
    flat = Secret()
    flat.metadata.name = PIPELINE_SERVER_SECRET
    flat.metadata.namespace = "ctrl-ns"
    flat.string_data = {"api_endpoint": "https://flat:8443", "cos_bucket": "b"}
    client.create(flat)
    NotebookWebhook(client, config).register(store)
    client.create(mk_nb("flat"))
    secret = client.get(Secret, "user", ELYRA_SECRET_NAME)
    cfg = json.loads(secret.string_data["odh_dsp.json"])
    assert cfg["metadata"]["api_endpoint"] == "https://flat:8443"
    assert cfg["metadata"]["cos_bucket"] == "b"


def test_name_longer_than_dns_label_rejected_at_admission():
    """A 64+ char name can never materialize (the Service shares it);
    admission rejects with a clear message instead of a reconciler
    crash-loop. 63 chars passes (STS/route clamping handles the rest)."""
    store = Store()
    client = Client(store)
    NotebookWebhook(client, Config()).register(store)
    too_long = "n" * 64
    with pytest.raises(AdmissionDeniedError, match="63"):
        client.create(mk_nb(too_long))
    client.create(mk_nb("n" * 63))  # boundary OK
