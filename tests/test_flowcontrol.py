"""API priority & fairness (cluster/flowcontrol.py, ISSUE 13): flow-schema
classification, bounded seats with per-flow FIFO queues, round-robin seat
handover, queue-full/timeout shed via the 429+Retry-After idiom, the exempt
level leader-election traffic rides, the typed Client's sim-mode admission
gate, the metrics families, and the /debug/flowcontrol view.

Deterministic tier-1 tests (marker: flowcontrol); the ci/faults.sh overload
lane reruns these under REPEAT + RACECHECK=1 + INVCHECK=1.
"""
import json
import threading
import time

import pytest

from odh_kubeflow_tpu.api.coordination import Lease
from odh_kubeflow_tpu.api.core import ConfigMap
from odh_kubeflow_tpu.apimachinery import TooManyRequestsError
from odh_kubeflow_tpu.cluster import Client, Store
from odh_kubeflow_tpu.cluster.flowcontrol import (
    LEADER_ELECTION_FLOW,
    FlowController,
    FlowSchema,
    PriorityLevel,
    current_flow,
    flow_context,
)
from odh_kubeflow_tpu.runtime import Manager
from odh_kubeflow_tpu.runtime import metrics as rm

pytestmark = pytest.mark.flowcontrol


def mk_cm(name, ns="flows"):
    cm = ConfigMap()
    cm.metadata.name = name
    cm.metadata.namespace = ns
    cm.data = {"k": "v"}
    return cm


def wait_for(fn, timeout=5.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(0.005)
    raise AssertionError(f"timeout: {msg}")


# ---------------------------------------------------------------------------
# flow identity plumbing
# ---------------------------------------------------------------------------


def test_flow_context_nests_and_restores():
    assert current_flow() == ""
    with flow_context("notebook"):
        assert current_flow() == "notebook"
        with flow_context("tpu-job"):
            assert current_flow() == "tpu-job"
        assert current_flow() == "notebook"
    assert current_flow() == ""


def test_flow_context_is_thread_local():
    seen = {}

    def worker():
        seen["inner"] = current_flow()

    with flow_context("notebook"):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    assert seen["inner"] == ""  # not inherited across threads


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------


def test_classify_default_schemas():
    fc = FlowController()
    # leader-election traffic and Lease objects are exempt no matter what
    assert fc.classify(LEADER_ELECTION_FLOW).name == "exempt"
    assert fc.classify("anybody", kind="Lease").name == "exempt"
    # node machinery -> system
    assert fc.classify("kubelet", verb="write", kind="Pod").name == "system"
    assert fc.classify("scheduler").name == "system"
    # product reconcilers -> the protected workload class
    for flow in ("notebook", "probe-status", "culling", "inference-endpoint"):
        assert fc.classify(flow).name == "workload-high", flow
    # batch: by controller flow AND by kind (an anonymous admission storm
    # creating TPUJobs still contends in the batch budget)
    assert fc.classify("tpu-job").name == "batch"
    assert fc.classify("", verb="create", kind="TPUJob").name == "batch"
    # unclassified -> catch-all
    assert fc.classify("stranger", kind="ConfigMap").name == "default"


def test_schema_first_match_precedence_and_validation():
    fc = FlowController(
        schemas=[
            FlowSchema("narrow", "high", flows=("x",), verbs=("write",)),
            FlowSchema("wide", "low", flows=("x",)),
            FlowSchema("catch-all", "default"),
        ],
        levels=[
            PriorityLevel("high", seats=2),
            PriorityLevel("low", seats=2),
            PriorityLevel("default", seats=2),
        ],
    )
    assert fc.classify("x", verb="write").name == "high"
    assert fc.classify("x", verb="read").name == "low"
    assert fc.classify("y").name == "default"
    with pytest.raises(ValueError):
        FlowController(
            schemas=[FlowSchema("bad", "nonexistent-level")],
            levels=[PriorityLevel("default")],
        )


# ---------------------------------------------------------------------------
# admission: seats, queueing, shed
# ---------------------------------------------------------------------------


def tiny_controller(seats=1, queue_length=2, timeout=0.2):
    return FlowController(
        schemas=[FlowSchema("catch-all", "default")],
        levels=[PriorityLevel("default", seats=seats, queue_length=queue_length,
                              queue_timeout_s=timeout)],
    )


def test_seats_queue_and_queue_full_shed():
    fc = tiny_controller(seats=1, queue_length=1, timeout=5.0)
    first = fc.admit("a")  # takes the only seat
    granted = threading.Event()

    def waiter():
        with fc.admit("a"):
            granted.set()

    t = threading.Thread(target=waiter)
    t.start()
    wait_for(lambda: fc.summary()["default"]["queue_depth"] == 1, msg="queued")
    # queue full: the NEXT request sheds immediately with Retry-After
    with pytest.raises(TooManyRequestsError) as exc:
        fc.admit("a")
    assert exc.value.retry_after > 0
    assert not granted.is_set()
    first.release()  # freed seat goes to the queued waiter
    assert granted.wait(2)
    t.join(2)
    s = fc.summary()["default"]
    assert s["rejected"] == 1 and s["dispatched"] == 2 and s["queued"] == 1
    assert s["inflight"] == 0 and s["queue_depth"] == 0


def test_queue_timeout_sheds():
    fc = tiny_controller(seats=1, queue_length=4, timeout=0.15)
    hog = fc.admit("hog")
    t0 = time.monotonic()
    with pytest.raises(TooManyRequestsError):
        fc.admit("late")
    assert 0.1 <= time.monotonic() - t0 < 2.0
    assert fc.summary()["default"]["timed_out"] == 1
    hog.release()
    # the timed-out waiter was removed from the queue: a fresh request gets
    # the seat, it is not handed to a ghost
    with fc.admit("fresh"):
        pass
    assert fc.summary()["default"]["inflight"] == 0


def test_round_robin_across_flows():
    """One hot flow must not monopolize a level: freed seats hand over
    round-robin across flows, so order is A,B,A,A — not FIFO A,A,A,B."""
    fc = tiny_controller(seats=1, queue_length=16, timeout=10.0)
    hog = fc.admit("seed")
    order = []
    threads = []

    def waiter(flow):
        with fc.admit(flow):
            order.append(flow)

    for i, flow in enumerate(["A", "A", "A", "B"]):
        t = threading.Thread(target=waiter, args=(flow,))
        t.start()
        threads.append(t)
        wait_for(
            lambda n=i: fc.summary()["default"]["queue_depth"] == n + 1,
            msg=f"waiter {i} queued",
        )
    hog.release()
    for t in threads:
        t.join(5)
    assert order == ["A", "B", "A", "A"]


def test_exempt_level_never_queues_never_sheds():
    fc = FlowController()
    before = fc.summary()["exempt"]["dispatched"]
    tickets = [fc.admit(LEADER_ELECTION_FLOW) for _ in range(50)]
    s = fc.summary()["exempt"]
    assert s["inflight"] == 50  # way past any seat budget, all admitted
    assert s["rejected"] == 0 and s["timed_out"] == 0 and s["queue_depth"] == 0
    assert s["dispatched"] - before == 50
    for t in tickets:
        t.release()
    assert fc.summary()["exempt"]["inflight"] == 0


# ---------------------------------------------------------------------------
# the typed Client's sim-mode admission gate (store.flowcontrol)
# ---------------------------------------------------------------------------


def test_client_gates_through_store_flowcontrol():
    store = Store()
    store.flowcontrol = tiny_controller(seats=1, queue_length=0, timeout=0.05)
    client = Client(store)
    client.create(mk_cm("ok"))  # seat free: passes straight through
    assert store.flowcontrol.summary()["default"]["dispatched"] >= 1

    hog = store.flowcontrol.admit("hog")
    # queue_length=0: every attempt sheds; the client's bounded 429 retry
    # loop (MAX_THROTTLE_RETRIES) rides the Retry-After then surfaces it
    retries0 = rm.client_retries_total.value(cause="throttle")
    with pytest.raises(TooManyRequestsError):
        client.get(ConfigMap, "flows", "ok")
    assert rm.client_retries_total.value(cause="throttle") - retries0 == Client.MAX_THROTTLE_RETRIES
    hog.release()
    assert client.get(ConfigMap, "flows", "ok").data == {"k": "v"}


def test_client_flow_override_rides_exempt_level():
    """The elector's client sets flow='leader-election': its writes bypass a
    saturated level entirely (failover never queues behind the storm). The
    traffic is a real Lease — DEPLOYGUARD holds the elector identity to
    Lease-only, so a stand-in kind would (correctly) fail armed."""
    store = Store()
    store.flowcontrol = tiny_controller(seats=1, queue_length=0, timeout=0.05)
    hog = store.flowcontrol.admit("hog")
    try:
        elector_client = Client(store)
        elector_client.flow = LEADER_ELECTION_FLOW
        lease = Lease()
        lease.metadata.namespace = "flows"
        lease.metadata.name = "mgr"
        elector_client.create(lease)  # admitted despite saturation
        s = store.flowcontrol.summary()
        assert s["exempt"]["rejected"] == 0 and s["exempt"]["dispatched"] >= 1
    finally:
        hog.release()


def test_thread_local_flow_reaches_client_gate():
    store = Store()
    fc = FlowController()
    store.flowcontrol = fc
    client = Client(store)
    before = fc.summary()["batch"]["dispatched"]
    with flow_context("tpu-job"):
        client.create(mk_cm("from-batch"))
    assert fc.summary()["batch"]["dispatched"] == before + 1


# ---------------------------------------------------------------------------
# observability: metrics families + /debug/flowcontrol
# ---------------------------------------------------------------------------


@pytest.mark.observability
def test_flowcontrol_metrics_families_move():
    rejected0 = rm.flowcontrol_requests_total.value(level="default", outcome="rejected")
    dispatched0 = rm.flowcontrol_requests_total.value(level="default", outcome="dispatched")
    fc = tiny_controller(seats=1, queue_length=0, timeout=0.05)
    with fc.admit("a"):
        with pytest.raises(TooManyRequestsError):
            fc.admit("b")
    assert rm.flowcontrol_requests_total.value(level="default", outcome="rejected") == rejected0 + 1
    assert rm.flowcontrol_requests_total.value(level="default", outcome="dispatched") == dispatched0 + 1
    assert rm.flowcontrol_inflight.value(level="default") == 0
    text = rm.global_registry.render()
    for family in (
        "flowcontrol_inflight",
        "flowcontrol_queue_depth",
        "flowcontrol_requests_total",
        "flowcontrol_wait_seconds_bucket",
    ):
        assert family in text, family


@pytest.mark.observability
def test_debug_flowcontrol_view():
    import urllib.request

    store = Store()
    store.flowcontrol = FlowController()
    with store.flowcontrol.admit("tpu-job"):
        pass
    mgr = Manager(store)
    server = mgr.serve_endpoints(metrics_port=0, health_port=0, host="127.0.0.1")
    try:
        host, port = server.metrics_address
        with urllib.request.urlopen(
            f"http://{host}:{port}/debug/flowcontrol", timeout=5
        ) as resp:
            payload = json.loads(resp.read().decode())
        levels = payload["levels"]
        assert set(levels) == {
            "exempt", "system", "workload-high", "serving", "batch", "default"
        }
        assert levels["batch"]["dispatched"] >= 1
        assert levels["exempt"]["exempt"] is True
        # the index page links the view
        with urllib.request.urlopen(f"http://{host}:{port}/debug/", timeout=5) as resp:
            assert "/debug/flowcontrol" in resp.read().decode()
    finally:
        server.stop()
