"""Fleet chip-time accounting ledger (runtime/accounting.py, ISSUE 17).

The ChipAccountant attributes every chip-second to one
(workload_class, object, phase) bucket per tick, with a hard conservation
contract: summed phase chip-seconds == physical chips x wall-clock. These
tests drive it on an injected sim clock through the phase taxonomy's real
transitions (suspend -> warm pool, silent host failure -> repair, pool
claim -> bind -> running), soak it under a seeded mixed bad day, exercise
/debug/accounting, prove the INVCHECK-armed conservation check catches a
doctored double-attribution (and is inert + cheap disarmed), and pin the
goodput-view migration: job/slice goodput are now views over GoodputLedger
with the reset_for_test() the old module-level accumulators never had.

Deterministic tier-1 tests (marker: accounting); ci/slo_lint.sh lint-checks
the exported families against the same live registry.
"""
import json
import time
import urllib.error
import urllib.request
from datetime import datetime, timezone

import pytest

from odh_kubeflow_tpu.api.core import (
    Container,
    Node,
    Pod,
    ResourceRequirements,
)
from odh_kubeflow_tpu.api.job import TPUJob
from odh_kubeflow_tpu.api.notebook import Notebook
from odh_kubeflow_tpu.api.notebook.v1beta1 import TPUStatus
from odh_kubeflow_tpu.cluster import SimCluster
from odh_kubeflow_tpu.cluster.slicepool import (
    POOL_CLAIMED_BY_ANNOTATION,
    POOL_PRIORITY_ANNOTATION,
    POOL_STATE_ANNOTATION,
    POOL_STATE_CLAIMED,
    POOL_STATE_WARM,
)
from odh_kubeflow_tpu.controllers import constants as CC
from odh_kubeflow_tpu.runtime import accounting
from odh_kubeflow_tpu.runtime.accounting import Attribution, ChipAccountant
from odh_kubeflow_tpu.tpu import TPU_RESOURCE
from odh_kubeflow_tpu.utils import invcheck

pytestmark = pytest.mark.accounting

CHIPS_PER_SLICE = 4  # v5e 2x2: one host, four chips


def iso(t):
    return (
        datetime.fromtimestamp(t, tz=timezone.utc)
        .isoformat()
        .replace("+00:00", "Z")
    )


class Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


class World:
    """SimCluster + sim-clocked accountant + the annotation levers the
    classifier reads (the same levers the real controllers pull)."""

    def __init__(self, slices=3, idle_after_s=100.0):
        self.cluster = SimCluster().start()
        self.cluster.add_tpu_pool("acct", "v5e", "2x2", slices=slices)
        self.clock = Clock()
        self.acct = ChipAccountant(
            self.cluster.client, idle_after_s=idle_after_s, clock=self.clock
        )
        self.client = self.cluster.client

    def stop(self):
        self.cluster.stop()

    def tick_to(self, t_end, step=5.0):
        while self.clock.t < t_end:
            self.clock.advance(min(step, t_end - self.clock.t))
            self.acct.tick()

    def add_notebook(self, name, mesh_ready=True, activity_at=0.0):
        nb = Notebook()
        nb.metadata.name = name
        nb.metadata.namespace = "t"
        nb.metadata.annotations[CC.LAST_ACTIVITY_ANNOTATION] = iso(activity_at)
        nb.status.tpu = TPUStatus(mesh_ready=mesh_ready)
        self.client.create(nb)
        return nb

    def annotate(self, kind, name, key, value):
        obj = self.client.get(kind, "t", name)
        if value is None:
            obj.metadata.annotations.pop(key, None)
        else:
            obj.metadata.annotations[key] = value
        self.client.update(obj)

    def annotate_node(self, pool, updates):
        node = self.client.get(Node, "", f"{pool}-w0")
        for k, v in updates.items():
            if v is None:
                node.metadata.annotations.pop(k, None)
            else:
                node.metadata.annotations[k] = v
        self.client.update(node)

    def bind_pod(self, name, pool, owner_label, owner):
        pod = Pod()
        pod.metadata.name = name
        pod.metadata.namespace = "t"
        pod.metadata.labels = {owner_label: owner}
        pod.spec.node_name = f"{pool}-w0"
        pod.spec.containers = [Container(
            name="tpu",
            image="work:1",
            resources=ResourceRequirements(
                requests={TPU_RESOURCE: str(CHIPS_PER_SLICE)}
            ),
        )]
        self.client.create(pod)
        return pod


@pytest.fixture
def world():
    w = World()
    yield w
    w.stop()


# ---------------------------------------------------------------------------
# phase-transition attribution on the sim clock
# ---------------------------------------------------------------------------


def test_suspend_episode_attributes_drain_then_warm_hold(world):
    """ready -> (checkpointing) draining -> release-to-warm-pool held on the
    suspended owner's behalf (suspended-warm), with the OTHER free slice
    staying pool-free: the warm/free split is counted owner-side."""
    world.add_notebook("nb-a")
    world.bind_pod("nb-a-pod", "acct-0", CC.NOTEBOOK_NAME_LABEL, "nb-a")
    world.acct.tick()  # baseline at t=0
    world.tick_to(20)  # 20s ready
    world.annotate(Notebook, "nb-a", CC.TPU_SUSPEND_STATE_ANNOTATION,
                   "checkpointing")
    world.tick_to(30)  # 10s draining
    world.client.delete(Pod, "t", "nb-a-pod")
    world.annotate(Notebook, "nb-a", CC.TPU_SUSPEND_STATE_ANNOTATION,
                   "suspended")
    world.annotate_node("acct-0", {
        POOL_STATE_ANNOTATION: POOL_STATE_WARM,
        POOL_PRIORITY_ANNOTATION: "10",
    })
    world.annotate_node("acct-1", {POOL_STATE_ANNOTATION: POOL_STATE_WARM})
    world.tick_to(50)  # 20s suspended-warm (one slice), warm-surplus free

    acct = world.acct
    assert acct.chip_seconds(phase="ready") == 20 * CHIPS_PER_SLICE
    assert acct.chip_seconds(phase="draining") == 10 * CHIPS_PER_SLICE
    # ONE warm slice is held for the one suspended owner; the second warm
    # slice and the never-pooled third slice are free capacity
    assert acct.chip_seconds(phase="suspended-warm") == 20 * CHIPS_PER_SLICE
    assert acct.chip_seconds(phase="pool-free") == (
        50 * CHIPS_PER_SLICE  # acct-2 free the whole episode
        + 30 * CHIPS_PER_SLICE  # acct-1 free until warm-marked, then surplus
        + 20 * CHIPS_PER_SLICE  # acct-1 as warm surplus
    )
    cons = acct.conservation()
    assert cons["residual_ratio"] == 0.0
    assert cons["physical_chip_seconds"] == 50 * 3 * CHIPS_PER_SLICE


def test_repair_episode_attributes_to_owner_not_pool(world):
    """A silently failed host under a bound notebook banks repairing
    chip-seconds AGAINST that notebook (the owner holds the broken slice),
    then returns to ready after restore."""
    world.add_notebook("nb-r")
    world.bind_pod("nb-r-pod", "acct-0", CC.NOTEBOOK_NAME_LABEL, "nb-r")
    world.acct.tick()
    world.tick_to(10)
    world.cluster.fail_node("acct-0-w0")
    world.tick_to(40)
    world.cluster.restore_node("acct-0-w0")
    world.tick_to(50)

    acct = world.acct
    assert acct.chip_seconds(phase="repairing") == 30 * CHIPS_PER_SLICE
    assert acct.chip_seconds(workload_class="notebook", phase="repairing") \
        == 30 * CHIPS_PER_SLICE
    assert acct.chip_seconds(phase="ready") == 20 * CHIPS_PER_SLICE
    snap = acct.snapshot(workload_class="notebook")
    assert snap["objects"][0]["object"] == "t/nb-r"
    assert snap["objects"][0]["chip_seconds"] == 50 * CHIPS_PER_SLICE


def test_reclaim_episode_claim_window_then_job_phases(world):
    """claimed-but-unbound is reclaim-churn billed to the CLAIMER, the bind
    lands as starting until the job runs, then ready."""
    world.acct.tick()
    world.annotate_node("acct-0", {
        POOL_STATE_ANNOTATION: POOL_STATE_CLAIMED,
        POOL_CLAIMED_BY_ANNOTATION: "t/train-z",
    })
    world.tick_to(15)  # claim->bind window
    job = TPUJob()
    job.metadata.name = "train-z"
    job.metadata.namespace = "t"
    job.metadata.annotations[CC.JOB_STATE_ANNOTATION] = "admitted"
    world.client.create(job)
    world.annotate_node("acct-0", {
        POOL_STATE_ANNOTATION: None,
        POOL_CLAIMED_BY_ANNOTATION: None,
    })
    world.bind_pod("train-z-pod", "acct-0", CC.JOB_NAME_LABEL, "train-z")
    world.tick_to(25)  # admitted = starting
    world.annotate(TPUJob, "train-z", CC.JOB_STATE_ANNOTATION, "running")
    world.tick_to(55)  # running = ready

    acct = world.acct
    assert acct.chip_seconds(phase="reclaim-churn") == 15 * CHIPS_PER_SLICE
    # the claim window is billed to the claimer object, not anonymous pool
    churn = [
        r for r in acct.snapshot()["objects"] if r["object"] == "t/train-z"
    ]
    # the claim window rides the claimer's name (class pool), the bound
    # phases ride the job class — together the whole 55s episode
    assert sum(r["chip_seconds"] for r in churn) == 55 * CHIPS_PER_SLICE
    assert any(
        r["workload_class"] == "pool"
        and r["chip_seconds"] == 15 * CHIPS_PER_SLICE
        for r in churn
    )
    assert acct.chip_seconds(workload_class="job", phase="starting") \
        == 10 * CHIPS_PER_SLICE
    assert acct.chip_seconds(workload_class="job", phase="ready") \
        == 30 * CHIPS_PER_SLICE


def test_stale_activity_turns_ready_into_idle_bound(world):
    world.add_notebook("nb-i", activity_at=0.0)
    world.bind_pod("nb-i-pod", "acct-0", CC.NOTEBOOK_NAME_LABEL, "nb-i")
    world.acct.tick()
    world.tick_to(100)  # activity fresh enough: ready
    world.tick_to(160)  # past idle_after_s=100: idle-bound
    assert world.acct.chip_seconds(phase="ready") == 100 * CHIPS_PER_SLICE
    assert world.acct.chip_seconds(phase="idle-bound") == 60 * CHIPS_PER_SLICE
    # fresh activity flips it back
    world.annotate(Notebook, "nb-i", CC.LAST_ACTIVITY_ANNOTATION, iso(160))
    world.tick_to(180)
    assert world.acct.chip_seconds(phase="ready") == 120 * CHIPS_PER_SLICE


# ---------------------------------------------------------------------------
# conservation under a seeded mixed bad-day soak
# ---------------------------------------------------------------------------


def test_conservation_holds_under_seeded_mixed_soak(monkeypatch):
    """Random (seeded) suspend/fail/claim/bind churn across notebook +
    inference + job owners, INVCHECK armed the whole soak: every tick
    re-verifies the exhaustive/exclusive classification and the final
    ledger balances to ZERO residual against physical chips x wall."""
    import os
    import random

    from odh_kubeflow_tpu.api.inference import InferenceEndpoint

    # INVCHECK is armed around every TICK (the conservation check under
    # test) but not around the chaos writes themselves: the injected
    # annotation flips deliberately skip the controllers, so the store's
    # machine-transition monitor would (correctly) flag them
    monkeypatch.delenv("INVCHECK", raising=False)

    def armed_tick(acct):
        os.environ["INVCHECK"] = "1"
        try:
            return acct.tick()
        finally:
            os.environ.pop("INVCHECK", None)

    rng = random.Random(1734)
    w = World(slices=6)
    try:
        # one owner of each class, plus two extra notebooks
        for i in range(3):
            w.add_notebook(f"nb-{i}")
            w.bind_pod(f"nb-{i}-pod", f"acct-{i}", CC.NOTEBOOK_NAME_LABEL,
                       f"nb-{i}")
        ep = InferenceEndpoint()
        ep.metadata.name = "ep-0"
        ep.metadata.namespace = "t"
        ep.metadata.annotations[CC.INFERENCE_STATE_ANNOTATION] = "serving"
        w.client.create(ep)
        w.bind_pod("ep-0-pod", "acct-3", CC.INFERENCE_NAME_LABEL, "ep-0")
        job = TPUJob()
        job.metadata.name = "job-0"
        job.metadata.namespace = "t"
        job.metadata.annotations[CC.JOB_STATE_ANNOTATION] = "running"
        w.client.create(job)
        w.bind_pod("job-0-pod", "acct-4", CC.JOB_NAME_LABEL, "job-0")

        armed_tick(w.acct)
        failed = set()
        for step in range(120):
            op = rng.randrange(8)
            if op == 0:
                w.annotate(Notebook, f"nb-{rng.randrange(3)}",
                           CC.TPU_SUSPEND_STATE_ANNOTATION,
                           rng.choice(["checkpointing", "suspended",
                                       "resuming", None]))
            elif op == 1:
                node = f"acct-{rng.randrange(6)}-w0"
                if node in failed:
                    failed.discard(node)
                    w.cluster.restore_node(node)
                else:
                    failed.add(node)
                    w.cluster.fail_node(node)
            elif op == 2:
                w.annotate_node("acct-5", {
                    POOL_STATE_ANNOTATION: rng.choice(
                        [POOL_STATE_WARM, POOL_STATE_CLAIMED, None]
                    ),
                    POOL_CLAIMED_BY_ANNOTATION: rng.choice(
                        ["t/job-0", None]
                    ),
                })
            elif op == 3:
                w.annotate(InferenceEndpoint, "ep-0",
                           CC.INFERENCE_STATE_ANNOTATION,
                           rng.choice(["serving", "draining", "loading",
                                       "suspended"]))
            elif op == 4:
                w.annotate(TPUJob, "job-0", CC.JOB_STATE_ANNOTATION,
                           rng.choice(["admitted", "running",
                                       "checkpointing", "preempted"]))
            elif op == 5:
                w.annotate(Notebook, f"nb-{rng.randrange(3)}",
                           CC.LAST_ACTIVITY_ANNOTATION,
                           iso(max(0.0, w.clock.t - rng.randrange(300))))
            # ops 6-7: quiet steps (pure time passage)
            w.clock.advance(rng.choice([1.0, 3.0, 7.0]))
            armed_tick(w.acct)

        cons = w.acct.conservation()
        assert cons["physical_chip_seconds"] == pytest.approx(
            6 * CHIPS_PER_SLICE * w.clock.t
        )
        assert cons["residual_ratio"] < 0.01  # the acceptance tolerance
        assert cons["residual_ratio"] < 1e-6  # in practice: exact by construction
        # zero unattributed chip-seconds: every TPU node classified each tick
        attrs = w.acct.classify()
        tpu_nodes = {
            n.metadata.name
            for n in w.client.list(Node)
            if int(n.status.capacity.get(TPU_RESOURCE, "0") or 0) > 0
        }
        assert {a.node for a in attrs} == tpu_nodes
        assert len(attrs) == len(tpu_nodes)
    finally:
        w.stop()


# ---------------------------------------------------------------------------
# the armed conservation check: doctored books caught red; disarmed inert
# ---------------------------------------------------------------------------


def _doctor_double_count(acct):
    real = acct.classify

    def doctored(now=None):
        attrs = real(now)
        return attrs + [attrs[0]]  # first node banked twice

    acct.classify = doctored


def test_armed_check_catches_doctored_double_count(world, monkeypatch):
    monkeypatch.setenv("INVCHECK", "1")
    world.acct.tick()
    world.clock.advance(5.0)
    world.acct.tick()  # honest books pass
    _doctor_double_count(world.acct)
    world.clock.advance(5.0)
    with pytest.raises(invcheck.InvariantViolation) as excinfo:
        world.acct.tick()
    assert "chip-conservation" in str(excinfo.value)
    assert "double-counted" in str(excinfo.value)


def test_armed_check_catches_unknown_phase(world, monkeypatch):
    monkeypatch.setenv("INVCHECK", "1")
    real = world.acct.classify

    def doctored(now=None):
        attrs = real(now)
        return [Attribution(a.node, a.chips, a.workload_class, a.obj,
                            "vibing") for a in attrs]

    world.acct.classify = doctored
    world.acct.tick()
    world.clock.advance(5.0)
    with pytest.raises(invcheck.InvariantViolation):
        world.acct.tick()


def test_disarmed_check_is_inert(world, monkeypatch):
    """INVCHECK off: the same doctored books tick through without raising —
    the armed check is opt-in, never a production tax."""
    monkeypatch.delenv("INVCHECK", raising=False)
    world.acct.tick()
    _doctor_double_count(world.acct)
    world.clock.advance(5.0)
    banked = world.acct.tick()
    assert banked > 0  # ticked, no raise (the doctoring went unchallenged)


def test_armed_conservation_overhead_under_ten_percent(monkeypatch):
    """The armed re-verification must stay O(attributions-per-tick) cheap:
    <10% added wall per tick against the disarmed baseline (absolute floor
    absorbs CI scheduler noise, the jaxguard/invcheck overhead idiom)."""
    w = World(slices=8)
    try:
        for i in range(4):
            w.add_notebook(f"nb-{i}")
            w.bind_pod(f"nb-{i}-pod", f"acct-{i}", CC.NOTEBOOK_NAME_LABEL,
                       f"nb-{i}")
        n = 60

        def run_ticks():
            w.acct.reset_for_test()
            w.acct.tick()
            t0 = time.perf_counter()
            for _ in range(n):
                w.clock.advance(1.0)
                w.acct.tick()
            return time.perf_counter() - t0

        monkeypatch.delenv("INVCHECK", raising=False)
        disarmed = min(run_ticks() for _ in range(3))
        monkeypatch.setenv("INVCHECK", "1")
        armed = min(run_ticks() for _ in range(3))
        assert armed - disarmed < max(0.10 * disarmed, 0.05), (
            f"armed {armed:.4f}s vs disarmed {disarmed:.4f}s over {n} ticks"
        )
    finally:
        w.stop()


# ---------------------------------------------------------------------------
# /debug/accounting
# ---------------------------------------------------------------------------


class _StubManager:
    def __init__(self):
        from odh_kubeflow_tpu.runtime.metrics import Registry

        self.metrics = Registry()

    def healthz(self):
        return True

    def readyz(self):
        return True


@pytest.fixture
def endpoints():
    from odh_kubeflow_tpu.runtime.serving import ServingEndpoints

    mgr = _StubManager()
    ep = ServingEndpoints(
        mgr, metrics_port=0, health_port=0, host="127.0.0.1"
    ).start()
    yield ep, mgr
    ep.stop()
    accounting.set_current(None)


def _get(ep, path):
    host, port = ep.metrics_address
    with urllib.request.urlopen(
        f"http://{host}:{port}{path}", timeout=5
    ) as r:
        return r.status, json.loads(r.read())


def test_debug_accounting_serves_ledger(world, endpoints):
    ep, mgr = endpoints
    world.add_notebook("nb-d")
    world.bind_pod("nb-d-pod", "acct-0", CC.NOTEBOOK_NAME_LABEL, "nb-d")
    world.acct.tick()
    world.tick_to(30)
    mgr.accountant = world.acct

    status, payload = _get(ep, "/debug/accounting")
    assert status == 200
    assert payload["ticks"] > 0
    assert payload["chip_seconds"]["residual_ratio"] == 0.0
    assert payload["chip_seconds"]["by_phase"]["ready"] \
        == 30 * CHIPS_PER_SLICE
    assert payload["fleet_utilization"] is not None
    assert "job" in payload["goodput_views"]

    # ?class= filters the object rows; ?limit= caps them
    status, payload = _get(ep, "/debug/accounting?class=notebook")
    assert status == 200
    assert all(
        r["workload_class"] == "notebook" for r in payload["objects"]
    )
    assert payload["objects"][0]["object"] == "t/nb-d"
    status, payload = _get(ep, "/debug/accounting?limit=0")
    assert status == 200 and payload["objects"] == []
    status, payload = _get(
        ep, "/debug/accounting?class=pool&object=acct-1"
    )
    assert status == 200
    assert [r["object"] for r in payload["objects"]] == ["acct-1"]


def test_debug_accounting_falls_back_to_module_handle(world, endpoints):
    ep, _mgr = endpoints  # stub manager has NO accountant attribute
    world.acct.tick()
    accounting.set_current(world.acct)
    status, payload = _get(ep, "/debug/accounting")
    assert status == 200 and "chip_seconds" in payload


def test_debug_accounting_bad_args_and_disabled(world, endpoints):
    ep, mgr = endpoints
    mgr.accountant = world.acct
    host, port = ep.metrics_address
    for query in ("?limit=nope", "?limit=-1", "?class=flywheel"):
        with pytest.raises(urllib.error.HTTPError) as excinfo:
            urllib.request.urlopen(
                f"http://{host}:{port}/debug/accounting{query}", timeout=5
            )
        assert excinfo.value.code == 400, query
    # no accountant anywhere -> 404 names the knob that enables it
    mgr.accountant = None
    accounting.set_current(None)
    with pytest.raises(urllib.error.HTTPError) as excinfo:
        urllib.request.urlopen(
            f"http://{host}:{port}/debug/accounting", timeout=5
        )
    assert excinfo.value.code == 404


def test_debug_index_links_accounting(endpoints):
    ep, _ = endpoints
    host, port = ep.metrics_address
    with urllib.request.urlopen(
        f"http://{host}:{port}/debug/", timeout=5
    ) as r:
        body = r.read().decode()
    assert "/debug/accounting" in body


def test_incident_bundle_freezes_accounting_snapshot(world):
    from odh_kubeflow_tpu.runtime.flightrecorder import FlightRecorder

    world.add_notebook("nb-f")
    world.bind_pod("nb-f-pod", "acct-0", CC.NOTEBOOK_NAME_LABEL, "nb-f")
    world.acct.tick()
    world.tick_to(10)
    accounting.set_current(world.acct)
    try:
        rec = FlightRecorder()
        rec.record("slice.degraded", notebook="t/nb-f", cause="test")
        incident_id = rec.snapshot("fleet-utilization", subject="fleet")
        bundle = rec.get(incident_id)
        assert bundle["accounting"]["ticks"] > 0
        assert bundle["accounting"]["chip_seconds"]["residual_ratio"] == 0.0
    finally:
        accounting.set_current(None)


# ---------------------------------------------------------------------------
# goodput views: the migrated integrators + the reset bugfix
# ---------------------------------------------------------------------------


def test_job_goodput_reset_between_tiers_regression():
    """ISSUE 17 bugfix: the old module-level _goodput dict survived across
    loadtest tiers, so a later tier's ratio inherited stale wall-clock.
    reset_for_test() starts a tier from the never-set state."""
    from odh_kubeflow_tpu.runtime import jobmetrics

    jobmetrics.reset_for_test()
    try:
        # tier 1: half the wall was productive
        jobmetrics.record_job_outcome(50.0, 100.0)
        assert jobmetrics.tpu_job_goodput_ratio.value() == pytest.approx(0.5)
        # back-to-back tier WITHOUT reset would blend: (50+100)/(100+100)
        jobmetrics.reset_for_test()
        assert jobmetrics.tpu_job_goodput_ratio.series() == []  # no-data
        jobmetrics.record_job_outcome(100.0, 100.0)
        assert jobmetrics.tpu_job_goodput_ratio.value() == pytest.approx(
            1.0
        ), "a fresh tier must not inherit the previous tier's wall-clock"
    finally:
        jobmetrics.reset_for_test()


def test_slice_goodput_view_over_shared_ledger():
    from odh_kubeflow_tpu.tpu import telemetry

    telemetry.goodput.reset_for_test()
    try:
        telemetry.goodput.observe(100.0, downtime_s=20.0)
        assert telemetry.slice_goodput_ratio.value() == pytest.approx(0.8)
        # both views surface in the accountant snapshot
        w = World(slices=1)
        try:
            views = w.acct.snapshot()["goodput_views"]
            assert views["slice"]["ratio"] == pytest.approx(0.8)
            assert views["slice"]["observed_s"] == pytest.approx(100.0)
        finally:
            w.stop()
        telemetry.goodput.reset_for_test()
        assert telemetry.slice_goodput_ratio.series() == []
    finally:
        telemetry.goodput.reset_for_test()
