"""Transport tier: the Kubernetes wire protocol over real sockets.

ApiServer (cluster/apiserver.py) serves a Store; RemoteStore
(cluster/remote.py) is the client. Together they are the build's
envtest: the same client bootstrap (kubeconfig, bearer token, TLS) works
against a real kube-apiserver, and the suite proves the protocol pieces the
controllers depend on — CRUD, conflicts, subresources, selectors, watch
streams with RV resume and 410 relist — over an actual HTTP connection.
Reference anchors: notebook-controller/main.go:79-94 (GetConfigOrDie),
odh controllers/suite_test.go:91-275 (envtest fixture).
"""
import threading
import time

import pytest

from odh_kubeflow_tpu.api.core import ConfigMap
from odh_kubeflow_tpu.api.notebook import Notebook
from odh_kubeflow_tpu.apimachinery import (
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
    UnauthorizedError,
)
from odh_kubeflow_tpu.cluster import ApiServer, Client, RemoteStore, Store
from odh_kubeflow_tpu.cluster.store import ADDED, DELETED, MODIFIED


@pytest.fixture()
def served():
    store = Store()
    server = ApiServer(store).start()
    remote = RemoteStore(server.base_url, timeout=5)
    yield store, server, remote
    server.stop()


def cm(name, ns="default", data=None):
    return {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {"name": name, "namespace": ns},
        "data": data or {},
    }


def test_crud_roundtrip_over_http(served):
    _, _, remote = served
    created = remote.create_raw(cm("alpha", data={"k": "v"}))
    assert created["metadata"]["resourceVersion"]
    got = remote.get_raw("v1", "ConfigMap", "default", "alpha")
    assert got["data"] == {"k": "v"}
    got["data"]["k"] = "v2"
    updated = remote.update_raw(got)
    assert updated["data"]["k"] == "v2"
    assert int(updated["metadata"]["resourceVersion"]) > int(
        created["metadata"]["resourceVersion"]
    )
    remote.delete_raw("v1", "ConfigMap", "default", "alpha")
    with pytest.raises(NotFoundError):
        remote.get_raw("v1", "ConfigMap", "default", "alpha")


def test_error_mapping(served):
    _, _, remote = served
    remote.create_raw(cm("dup"))
    with pytest.raises(AlreadyExistsError):
        remote.create_raw(cm("dup"))
    with pytest.raises(NotFoundError):
        remote.get_raw("v1", "ConfigMap", "default", "ghost")
    # stale-RV update maps to ConflictError across the wire
    stale = remote.get_raw("v1", "ConfigMap", "default", "dup")
    fresh = remote.get_raw("v1", "ConfigMap", "default", "dup")
    fresh["data"] = {"x": "1"}
    remote.update_raw(fresh)
    stale["data"] = {"y": "2"}
    with pytest.raises(ConflictError):
        remote.update_raw(stale)


def test_typed_client_over_remote_store(served):
    """The controller-facing Client works unchanged on the remote backend."""
    _, _, remote = served
    client = Client(remote)
    nb = Notebook()
    nb.metadata.name = "wire-nb"
    nb.metadata.namespace = "user"
    nb.spec.template.spec.containers = [{"name": "c", "image": "jax:1"}]
    client.create(nb)
    got = client.get(Notebook, "user", "wire-nb")
    assert got.metadata.uid
    got.metadata.annotations["touched"] = "yes"
    client.update(got)
    assert client.get(Notebook, "user", "wire-nb").metadata.annotations["touched"] == "yes"


def test_label_selector_and_all_namespace_list(served):
    _, _, remote = served
    remote.create_raw(cm("a", ns="one", data={}) | {})
    obj = cm("b", ns="two")
    obj["metadata"]["labels"] = {"app": "nb"}
    remote.create_raw(obj)
    all_items, rv = remote.list_raw_with_rv("v1", "ConfigMap")
    assert {o["metadata"]["name"] for o in all_items} == {"a", "b"}
    assert rv
    only_two = remote.list_raw("v1", "ConfigMap", namespace="two")
    assert [o["metadata"]["name"] for o in only_two] == ["b"]
    labeled = remote.list_raw("v1", "ConfigMap", label_selector={"app": "nb"})
    assert [o["metadata"]["name"] for o in labeled] == ["b"]


def test_status_subresource_over_http(served):
    _, _, remote = served
    nb = {
        "apiVersion": "kubeflow.org/v1beta1",
        "kind": "Notebook",
        "metadata": {"name": "nb", "namespace": "u"},
        "spec": {"template": {"spec": {"containers": []}}},
    }
    remote.create_raw(nb)
    cur = remote.get_raw("kubeflow.org/v1beta1", "Notebook", "u", "nb")
    cur["status"] = {"readyReplicas": 3}
    remote.update_raw(cur, subresource="status")
    got = remote.get_raw("kubeflow.org/v1beta1", "Notebook", "u", "nb")
    assert got["status"]["readyReplicas"] == 3
    # plain update cannot clobber status (subresource isolation over the wire)
    got["status"] = {"readyReplicas": 0}
    remote.update_raw(got)
    assert (
        remote.get_raw("kubeflow.org/v1beta1", "Notebook", "u", "nb")["status"][
            "readyReplicas"
        ]
        == 3
    )


def test_merge_patch_over_http(served):
    _, _, remote = served
    remote.create_raw(cm("p", data={"keep": "1", "drop": "2"}))
    out = remote.patch_raw(
        "v1", "ConfigMap", "default", "p", {"data": {"drop": None, "new": "3"}}
    )
    assert out["data"] == {"keep": "1", "new": "3"}


def test_json_patch_content_type(served):
    """RFC 6902 patches (the AdmissionReview patch format) are applied too."""
    import json
    import urllib.request

    _, server, remote = served
    remote.create_raw(cm("jp", data={"a": "1"}))
    ops = [{"op": "replace", "path": "/data/a", "value": "9"}]
    req = urllib.request.Request(
        server.base_url + "/api/v1/namespaces/default/configmaps/jp",
        data=json.dumps(ops).encode(),
        method="PATCH",
        headers={"Content-Type": "application/json-patch+json"},
    )
    with urllib.request.urlopen(req, timeout=5) as resp:
        out = json.loads(resp.read())
    assert out["data"]["a"] == "9"


def test_watch_stream_live_events(served):
    _, _, remote = served
    w = remote.watch("v1", "ConfigMap", namespace="default")
    assert w.pending == []
    remote.create_raw(cm("w1"))
    ev = w.get(timeout=5)
    assert ev.type == ADDED and ev.object["metadata"]["name"] == "w1"
    remote.patch_raw("v1", "ConfigMap", "default", "w1", {"data": {"x": "1"}})
    ev = w.get(timeout=5)
    assert ev.type == MODIFIED
    remote.delete_raw("v1", "ConfigMap", "default", "w1")
    ev = w.get(timeout=5)
    assert ev.type == DELETED
    w.stop()


def test_watch_initial_snapshot_then_live(served):
    _, _, remote = served
    remote.create_raw(cm("pre"))
    w = remote.watch("v1", "ConfigMap", namespace="default")
    first = w.get(timeout=5)  # synthetic ADDED from the list snapshot
    assert first.type == ADDED and first.object["metadata"]["name"] == "pre"
    remote.create_raw(cm("post"))
    ev = w.get(timeout=5)
    assert ev.object["metadata"]["name"] == "post"
    w.stop()


def test_watch_survives_connection_drop(served):
    """Reflector contract: a dropped stream reconnects from the last seen RV
    with no events lost and no duplicates."""
    store, server, remote = served
    w = remote.watch("v1", "ConfigMap", namespace="default")
    remote.create_raw(cm("before-drop"))
    assert w.get(timeout=5).object["metadata"]["name"] == "before-drop"
    # sever every server-side watch stream (the server keeps running)
    with server._watch_lock:
        for sw in list(server._active_watches):
            sw.stop()
    time.sleep(0.1)
    remote.create_raw(cm("after-drop"))
    ev = w.get(timeout=5)
    assert ev is not None and ev.object["metadata"]["name"] == "after-drop"
    w.stop()


def test_watch_410_relist_recovery():
    """When the resume window is gone the reflector relists and keeps going."""
    store = Store(watch_history_limit=4)
    server = ApiServer(store).start()
    remote = RemoteStore(server.base_url, timeout=5)
    try:
        w = remote.watch("v1", "ConfigMap", namespace="default")
        # blow past the watch history while the stream is severed
        with server._watch_lock:
            for sw in list(server._active_watches):
                sw.stop()
        for i in range(8):
            store.create_raw(cm(f"flood-{i}"))
        seen = set()
        deadline = time.time() + 10
        while len(seen) < 8 and time.time() < deadline:
            ev = w.get(timeout=0.5)
            if ev is not None and ev.type == ADDED:
                seen.add(ev.object["metadata"]["name"])
        assert seen == {f"flood-{i}" for i in range(8)}
        w.stop()
    finally:
        server.stop()


def test_bearer_token_auth():
    store = Store()
    server = ApiServer(store, bearer_token="sekret").start()
    try:
        anon = RemoteStore(server.base_url, timeout=5)
        with pytest.raises(UnauthorizedError):
            anon.list_raw("v1", "ConfigMap")
        authed = RemoteStore(server.base_url, token="sekret", timeout=5)
        authed.create_raw(cm("locked"))
        assert authed.get_raw("v1", "ConfigMap", "default", "locked")
    finally:
        server.stop()


def test_tls_and_kubeconfig(tmp_path):
    """HTTPS end-to-end with a generated CA + kubeconfig bootstrap — the
    GetConfigOrDie path against our own apiserver."""
    from odh_kubeflow_tpu.utils.certs import generate_cert_dir

    ca, crt, key = generate_cert_dir(str(tmp_path / "pki"))
    store = Store()
    server = ApiServer(store, bearer_token="tok", certfile=crt, keyfile=key).start()
    try:
        host, port = server.address
        kubeconfig = tmp_path / "kubeconfig"
        kubeconfig.write_text(
            f"""
apiVersion: v1
kind: Config
clusters:
- name: local
  cluster:
    server: https://127.0.0.1:{port}
    certificate-authority: {ca}
contexts:
- name: local
  context: {{cluster: local, user: admin}}
current-context: local
users:
- name: admin
  user: {{token: tok}}
"""
        )
        remote = RemoteStore.from_kubeconfig(str(kubeconfig))
        remote.timeout = 5
        remote.create_raw(cm("secure"))
        assert remote.get_raw("v1", "ConfigMap", "default", "secure")["metadata"]["name"] == "secure"
        w = remote.watch("v1", "ConfigMap", namespace="default")
        remote.create_raw(cm("secure2"))
        names = set()
        deadline = time.time() + 10
        while "secure2" not in names and time.time() < deadline:
            ev = w.get(timeout=0.5)
            if ev is not None:
                names.add(ev.object["metadata"]["name"])
        assert "secure2" in names
        w.stop()
    finally:
        server.stop()


def test_cluster_scoped_resources(served):
    _, _, remote = served
    node = {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {"name": "node-1", "labels": {"pool": "tpu"}},
        "spec": {},
    }
    remote.create_raw(node)
    assert remote.get_raw("v1", "Node", "", "node-1")["metadata"]["name"] == "node-1"
    assert [o["metadata"]["name"] for o in remote.list_raw("v1", "Node")] == ["node-1"]
    remote.delete_raw("v1", "Node", "", "node-1")
    with pytest.raises(NotFoundError):
        remote.get_raw("v1", "Node", "", "node-1")


def test_spoke_version_over_http(served):
    """Multi-version serving: the storage alias works across the wire."""
    _, _, remote = served
    nb = {
        "apiVersion": "kubeflow.org/v1",
        "kind": "Notebook",
        "metadata": {"name": "spoke", "namespace": "u"},
        "spec": {"template": {"spec": {"containers": []}}},
    }
    remote.create_raw(nb)
    got = remote.get_raw("kubeflow.org/v1beta1", "Notebook", "u", "spoke")
    assert got["metadata"]["name"] == "spoke"


def test_watch_label_selector_filtering(served):
    """?watch=true&labelSelector=... filters the stream server-side."""
    import json as _json
    import urllib.request

    _, server, remote = served
    url = (
        server.base_url
        + "/api/v1/namespaces/default/configmaps?watch=true&labelSelector=app%3Dnb"
    )
    resp = urllib.request.urlopen(url, timeout=5)
    try:
        labeled = cm("match")
        labeled["metadata"]["labels"] = {"app": "nb"}
        remote.create_raw(cm("nomatch"))
        remote.create_raw(labeled)
        line = resp.readline()
        ev = _json.loads(line)
        assert ev["object"]["metadata"]["name"] == "match"
    finally:
        from odh_kubeflow_tpu.cluster.remote import _abort_stream

        _abort_stream(resp)


def test_in_cluster_config(tmp_path, monkeypatch):
    """rest.InClusterConfig analog: apiserver address from the pod env,
    bearer token + CA from the ServiceAccount mount."""
    from odh_kubeflow_tpu.utils.certs import generate_cert_dir

    ca, crt, key = generate_cert_dir(str(tmp_path / "pki"))
    store = Store()
    server = ApiServer(store, bearer_token="sa-token", certfile=crt, keyfile=key).start()
    try:
        sa_dir = tmp_path / "serviceaccount"
        sa_dir.mkdir()
        (sa_dir / "token").write_text("sa-token\n")
        import shutil

        shutil.copy(ca, sa_dir / "ca.crt")
        host, port = server.address
        monkeypatch.setenv("KUBERNETES_SERVICE_HOST", "127.0.0.1")
        monkeypatch.setenv("KUBERNETES_SERVICE_PORT", str(port))
        remote = RemoteStore.in_cluster(sa_dir=str(sa_dir))
        remote.timeout = 5
        remote.create_raw(cm("from-pod"))
        assert remote.get_raw("v1", "ConfigMap", "default", "from-pod")

        monkeypatch.delenv("KUBERNETES_SERVICE_HOST")
        with pytest.raises(RuntimeError, match="not in a cluster"):
            RemoteStore.in_cluster(sa_dir=str(sa_dir))
    finally:
        server.stop()


def test_watch_bookmarks_advance_resume_rv():
    """allowWatchBookmarks end to end: a watch on a kind with NO traffic
    still advances its resume RV from server BOOKMARK events (other kinds
    move the global RV), so reconnecting after a long quiet period does not
    410 even when the history window has rolled past the last seen event."""
    store = Store(watch_history_limit=4)
    server = ApiServer(store, heartbeat_polls=1).start()  # bookmark ~0.5s
    remote = RemoteStore(server.base_url, timeout=5)
    try:
        w = remote.watch("v1", "ConfigMap", namespace="quiet")
        rv0 = w._rv
        # traffic on a DIFFERENT namespace: the quiet watch sees no events
        # (namespace-scoped), but bookmarks carry the advancing global RV
        for i in range(6):
            store.create_raw(cm(f"noise-{i}", ns="other"))
        deadline = time.time() + 10
        while w._rv == rv0 and time.time() < deadline:
            time.sleep(0.1)
        assert int(w._rv) > int(rv0 or "0"), "bookmark never advanced the RV"
        assert w.get(timeout=0.2) is None  # bookmarks surface no events
        w.stop()
    finally:
        server.stop()


def test_wire_fixture_debug_escapes(tmp_path, monkeypatch):
    """ODH_WIRE_DEBUG_DIR (envtest suite_test.go:125-155 analog): the fixture
    exports a kubeconfig a SECOND client can bootstrap from, and an apiserver
    audit log records every request with its outcome."""
    import json as _json

    from odh_kubeflow_tpu.cluster.remote_fixture import build_remote_stack
    from odh_kubeflow_tpu.controllers import Config

    monkeypatch.setenv("ODH_WIRE_DEBUG_DIR", str(tmp_path))
    teardown = []
    try:
        _, remote, _ = build_remote_stack(Store(), Config(), teardown, token="dbg")
        remote.create_raw(cm("probe"))
        # a fresh client built ONLY from the exported kubeconfig
        second = RemoteStore.from_kubeconfig(path=str(tmp_path / "kubeconfig"))
        got = second.get_raw("v1", "ConfigMap", "default", "probe")
        assert got["metadata"]["name"] == "probe"
        with pytest.raises(NotFoundError):
            second.get_raw("v1", "ConfigMap", "default", "nope")
        lines = [
            _json.loads(line)
            for line in (tmp_path / "apiserver-audit.jsonl").read_text().splitlines()
        ]
        assert any(e["method"] == "POST" and e["outcome"] == "ok" for e in lines)
        assert any(e["outcome"].startswith("404") for e in lines)
    finally:
        for fn in reversed(teardown):
            fn()


def test_host_pool_retry_discipline():
    """HostPool's execute-at-most-once rules, pinned against stubbed
    connections (real sockets make the failure phase racy — http.client
    auto-reconnects after an advertised close, which never exercises the
    pool's own retry):
    - send-phase failure: retried once, ANY method (the server never parsed
      the request on that connection),
    - response-phase failure: retried only for GET; a POST/PATCH raises
      (the server may have executed it),
    - socket.timeout: never retried, either phase."""
    import socket

    import pytest

    from odh_kubeflow_tpu.cluster.remote import HostPool

    class FakeConn:
        def __init__(self, log, fail_send=None, fail_resp=None):
            self.log = log
            self.fail_send = fail_send
            self.fail_resp = fail_resp

        def request(self, method, path, body=None, headers=None):
            self.log.append(("send", method, path))
            if self.fail_send:
                err, self.fail_send = self.fail_send, None
                raise err

        def getresponse(self):
            if self.fail_resp:
                err, self.fail_resp = self.fail_resp, None
                raise err

            class R:
                status = 200

                @staticmethod
                def read():
                    return b"{}"

            return R()

        def close(self):
            self.log.append(("close",))

    def pool_with(conns):
        pool = HostPool("http", "x", 1, timeout=1)
        seq = iter(conns)
        pool._conn = lambda: next(seq)  # type: ignore[method-assign]
        return pool

    # send-phase failure: POST retried once, second conn carries it
    log = []
    pool = pool_with([FakeConn(log, fail_send=ConnectionResetError()),
                      FakeConn(log)])
    status, _ = pool.request("POST", "/p", b"{}", {})
    assert status == 200
    assert [e for e in log if e[0] == "send"] == [
        ("send", "POST", "/p"), ("send", "POST", "/p")
    ]

    # response-phase failure: GET retried...
    log = []
    pool = pool_with([FakeConn(log, fail_resp=ConnectionResetError()),
                      FakeConn(log)])
    status, _ = pool.request("GET", "/g", None, {})
    assert status == 200
    assert len([e for e in log if e[0] == "send"]) == 2

    # ...but a POST whose response fails must RAISE (server may have run it)
    log = []
    pool = pool_with([FakeConn(log, fail_resp=ConnectionResetError()),
                      FakeConn(log)])
    with pytest.raises(ConnectionResetError):
        pool.request("POST", "/p", b"{}", {})
    assert len([e for e in log if e[0] == "send"]) == 1

    # timeouts never retry, either phase or method
    for kwargs, method in (
        ({"fail_send": socket.timeout()}, "GET"),
        ({"fail_resp": socket.timeout()}, "GET"),
        ({"fail_resp": socket.timeout()}, "POST"),
    ):
        log = []
        pool = pool_with([FakeConn(log, **kwargs), FakeConn(log)])
        with pytest.raises(socket.timeout):
            pool.request(method, "/t", None, {})
        assert len([e for e in log if e[0] == "send"]) == 1
