"""Apiserver overload resilience (ISSUE 13 satellite): the deterministic
`apiserver_overload` fault schedule (429 bursts + latency injection) runs
under a TPUJob admission storm against a flow-controlled control plane.

Invariants the overload lane (ci/faults.sh) replays under REPEAT +
RACECHECK=1 + INVCHECK=1:
- the storm is shed at the batch priority level (rejected/timed_out move),
- exempt-level (leader lease) traffic is NEVER starved — zero sheds while
  renewals keep flowing through the storm,
- the protected workload class is untouched,
- zero silently-stuck objects: every job (storm jobs included) reaches
  `succeeded`, every notebook reaches Ready, no controller thread dies.
"""
import json
import threading
import time

import pytest

from odh_kubeflow_tpu.api.coordination import Lease
from odh_kubeflow_tpu.api.core import ConfigMap, Container
from odh_kubeflow_tpu.api.job import TPUJob
from odh_kubeflow_tpu.api.notebook import Notebook, TPUSpec
from odh_kubeflow_tpu.apimachinery import TooManyRequestsError
from odh_kubeflow_tpu.cluster import Client, SimCluster, Store
from odh_kubeflow_tpu.cluster.faults import FaultInjector, apiserver_overload
from odh_kubeflow_tpu.cluster.flowcontrol import (
    FlowController,
    PriorityLevel,
    default_flow_schemas,
    flow_context,
)
from odh_kubeflow_tpu.controllers import (
    Config,
    NotebookReconciler,
    ProbeStatusController,
    SuspendResumeController,
    TPUJobReconciler,
    constants as C,
)
from odh_kubeflow_tpu.controllers.job import STATE_SUCCEEDED
from odh_kubeflow_tpu.probe import sim_agent_behavior
from odh_kubeflow_tpu.runtime import Manager

pytestmark = pytest.mark.overload

NS = "overload"
STEP_PER_CKPT = 30

FAST = Config(
    enable_culling=False,
    suspend_enabled=True,
    readiness_probe_period_s=0.15,
    suspend_checkpoint_window_s=1.0,
    resume_timeout_s=20.0,
    reclaim_pending_grace_s=0.3,
    job_checkpoint_window_s=2.0,
    job_requeue_backoff_s=0.1,
)


def storm_flowcontrol():
    """Default schemas over default levels, with the batch budget tightened
    so a create storm contends deterministically (2 seats, 2-deep queues,
    200ms queue patience)."""
    return FlowController(
        schemas=default_flow_schemas(),
        levels=[
            PriorityLevel("exempt", exempt=True),
            PriorityLevel("system", seats=16, queue_length=64, queue_timeout_s=10.0),
            PriorityLevel("workload-high", seats=12, queue_length=64,
                          queue_timeout_s=10.0),
            PriorityLevel("serving", seats=8, queue_length=32,
                          queue_timeout_s=5.0),
            PriorityLevel("batch", seats=2, queue_length=2, queue_timeout_s=0.2),
            PriorityLevel("default", seats=8, queue_length=32, queue_timeout_s=5.0),
        ],
    )


def mk_job(name, steps=30, period=0.1):
    job = TPUJob()
    job.metadata.name = name
    job.metadata.namespace = NS
    job.spec.template.spec.containers = [Container(name=name, image="jax:1")]
    job.spec.tpu = TPUSpec(accelerator="v5e", topology="2x2")
    job.spec.steps = steps
    job.spec.checkpoint_period_s = period
    return job


def mk_nb(name):
    nb = Notebook()
    nb.metadata.name = name
    nb.metadata.namespace = NS
    nb.spec.template.spec.containers = [Container(name=name, image="jax:1")]
    return nb


def wait_for(fn, timeout=60, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if fn():
                return True
        except TooManyRequestsError:
            pass  # the injected overload also hits the test's own reads
        time.sleep(0.05)
    raise AssertionError(f"timeout: {msg}")


def create_persistent(client, obj, attempts=60):
    """Driver-side storm retry loop: a shed create is re-offered until the
    level has room — the storm is slowed down, never lost."""
    for _ in range(attempts):
        try:
            return client.create(obj)
        except TooManyRequestsError:
            time.sleep(0.05)
    raise AssertionError(f"create never admitted: {obj.metadata.name}")


def test_overload_storm_shed_at_batch_exempt_never_starved():
    cluster = SimCluster().start()
    fc = storm_flowcontrol()
    cluster.store.flowcontrol = fc
    cluster.add_tpu_pool("v5e", "v5e", "2x2", slices=4)
    cluster.add_cpu_pool("cpu", nodes=2)
    apiserver_overload(cluster.faults, seed=13)

    steps = {}

    def http_get(url, timeout=10.0):
        if "/tpu/checkpoint" in url and "-learner-" in url:
            name = url.split("//", 1)[1].split("-learner-", 1)[0]
            steps[name] = steps.get(name, 0) + STEP_PER_CKPT
            return 200, json.dumps({"saved": True, "step": steps[name]}).encode()
        if "/tpu/checkpoint" in url:
            return 200, json.dumps({"saved": True, "step": 1}).encode()
        return cluster.http_get(url, timeout=timeout)

    # leader-elected manager with a short lease: renewals tick through the
    # whole storm, and every one of them must ride the exempt level
    mgr = Manager(cluster.store, leader_election=True,
                  leader_election_id="overload", lease_duration=2.0,
                  renew_period=0.2)
    NotebookReconciler(mgr, FAST).setup()
    ProbeStatusController(mgr, FAST, http_get=http_get).setup()
    SuspendResumeController(mgr, FAST, http_get=http_get).setup()
    TPUJobReconciler(mgr, FAST, http_get=http_get).setup()
    agents = {}
    cluster.add_pod_behavior(sim_agent_behavior(agents, duty=0.9))
    mgr.start(wait_for_leadership_timeout=5)
    driver = cluster.client
    try:
        for i in range(2):
            create_persistent(driver, mk_nb(f"nb-{i}"))
        base_jobs = [f"job-{i}" for i in range(3)]
        for name in base_jobs:
            create_persistent(driver, mk_job(name))

        # the admission storm: 6 anonymous TPUJob creates slam the batch
        # level while both its seats are held — queue-full sheds are
        # guaranteed, and the drivers must retry through them
        storm_jobs = [f"storm-{i}" for i in range(6)]
        hogs = [fc.admit("tpu-job") for _ in range(2)]
        exempt_before = fc.summary()["exempt"]["dispatched"]
        threads = [
            threading.Thread(target=create_persistent, args=(driver, mk_job(n)))
            for n in storm_jobs
        ]
        for t in threads:
            t.start()
        time.sleep(0.6)  # the storm beats on a saturated level
        for h in hogs:
            h.release()
        for t in threads:
            t.join(30)
            assert not t.is_alive(), "a storm driver wedged"

        # shed happened, at the batch level and ONLY there
        s = fc.summary()
        assert s["batch"]["rejected"] + s["batch"]["timed_out"] > 0
        assert s["workload-high"]["rejected"] == 0
        assert s["workload-high"]["timed_out"] == 0
        # exempt traffic kept flowing, with zero sheds: failover was never
        # starved by the storm
        assert s["exempt"]["rejected"] == 0 and s["exempt"]["timed_out"] == 0
        assert s["exempt"]["dispatched"] > exempt_before
        assert mgr.elector.is_leader.is_set()

        # zero silently-stuck objects: every job — storm jobs included —
        # completes once the overload budgets burn out
        def job_state(name):
            return driver.get(TPUJob, NS, name).metadata.annotations.get(
                C.JOB_STATE_ANNOTATION, "")

        for name in base_jobs + storm_jobs:
            wait_for(lambda n=name: job_state(n) == STATE_SUCCEEDED,
                     timeout=90, msg=f"{name} succeeded")
        for i in range(2):
            wait_for(
                lambda i=i: driver.get(Notebook, NS, f"nb-{i}").status.ready_replicas >= 1,
                msg=f"nb-{i} ready",
            )
        assert mgr.healthz(), "a controller thread died under overload"
    finally:
        mgr.stop()
        cluster.stop()
        cluster.faults.clear()


def test_wire_overload_flow_header_delay_and_429_bursts():
    """Wire mode: the X-Flow-Schema header classifies remote requests at the
    ApiServer's admission point, the overload schedule's latency + 429-burst
    rules fire at the HTTP boundary, and exempt Lease traffic is untouched."""
    pytest.importorskip("cryptography")  # TLS fixture needs it (like test_transport)
    from odh_kubeflow_tpu.cluster.remote_fixture import build_remote_stack

    store = Store()
    fc = FlowController()
    teardown = []
    try:
        _, remote, _ = build_remote_stack(store, Config(), teardown, flowcontrol=fc)
        store.faults = FaultInjector()  # after fixture setup: its own writes unthrottled
        rules = apiserver_overload(store.faults, seed=5)
        client = Client(remote)
        batch_before = fc.summary()["batch"]["dispatched"]
        with flow_context("tpu-job"):
            for i in range(10):
                cm = ConfigMap()
                cm.metadata.name = f"wire-{i}"
                cm.metadata.namespace = NS
                create_persistent(client, cm)
        lease = Lease()
        lease.metadata.name = "wire-lease"
        lease.metadata.namespace = "kube-system"
        create_persistent(client, lease)

        s = fc.summary()
        # the thread-local flow traveled the wire as X-Flow-Schema and landed
        # the creates on the batch level
        assert s["batch"]["dispatched"] - batch_before >= 10
        assert s["exempt"]["dispatched"] >= 1 and s["exempt"]["rejected"] == 0
        # both halves of the schedule actually fired at the HTTP boundary
        assert any(r.site == "apiserver.request" and r.action == "delay" and r.fired > 0
                   for r in rules)
        assert any(r.site == "apiserver.request" and r.error is not None and r.fired > 0
                   for r in rules)
        # nothing was lost to the bursts
        for i in range(10):
            assert client.get(ConfigMap, NS, f"wire-{i}").metadata.name == f"wire-{i}"
    finally:
        for fn in reversed(teardown):
            fn()
